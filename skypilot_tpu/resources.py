"""Resources: what hardware a task wants, TPU slices first-class.

Parity: /root/reference/sky/resources.py:30-1104 (cloud/instance/accelerator
request, '4+' cpu grammar, validation against catalog, `less_demanding_than`
reuse check, `get_cost`, YAML round-trip). TPU-first redesign:

* ``accelerators: tpu-v5p-64`` resolves to a :class:`TpuSliceSpec` — the
  slice (not a VM) is the launchable unit; no `instance_type: TPU-VM`
  sentinel and no `accelerator_args: {tpu_vm: ...}` legacy switch
  (reference resources.py:544-615).
* ``capacity: on_demand | spot | queued | reserved`` generalizes `use_spot`
  with GCP queued-resources and reservations (absent in the reference).
* ``num_slices`` requests a multislice (DCN-connected) job.
"""
from __future__ import annotations

import typing
from typing import Any, Dict, List, Optional, Set, Union

from skypilot_tpu import exceptions
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import accelerator_registry

if typing.TYPE_CHECKING:
    pass

_DEFAULT_DISK_SIZE_GB = 256


class Resources:
    """An (in)complete hardware request; becomes launchable once a cloud and
    a concrete shape (instance type or TPU slice) are filled in."""

    def __init__(
        self,
        cloud: Union[None, str, cloud_lib.Cloud] = None,
        instance_type: Optional[str] = None,
        accelerators: Union[None, str, Dict[str, int]] = None,
        cpus: Union[None, int, float, str] = None,
        memory: Union[None, int, float, str] = None,
        use_spot: Optional[bool] = None,
        capacity: Union[None, str, cloud_lib.ProvisionMode] = None,
        job_recovery: Optional[str] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        image_id: Optional[str] = None,
        disk_size: Optional[int] = None,
        ports: Optional[List[int]] = None,
        labels: Optional[Dict[str, str]] = None,
        accelerator_args: Optional[Dict[str, Any]] = None,
        num_slices: int = 1,
        _validate: bool = True,
    ) -> None:
        from skypilot_tpu.clouds import registry  # pylint: disable=import-outside-toplevel
        if isinstance(cloud, str):
            cloud = registry.from_str(cloud)
        self._cloud: Optional[cloud_lib.Cloud] = cloud
        self._instance_type = instance_type
        self._accelerators = self._parse_accelerators(accelerators)
        self._cpus = self._validate_count_str('cpus', cpus)
        self._memory = self._validate_count_str('memory', memory)

        if isinstance(capacity, str):
            capacity = cloud_lib.ProvisionMode(capacity.lower())
        if capacity is None:
            capacity = (cloud_lib.ProvisionMode.SPOT
                        if use_spot else cloud_lib.ProvisionMode.ON_DEMAND)
        elif use_spot is not None:
            want_spot = capacity is cloud_lib.ProvisionMode.SPOT
            if use_spot != want_spot:
                raise exceptions.InvalidTaskError(
                    f'use_spot={use_spot} conflicts with '
                    f'capacity={capacity.value}.')
        self._capacity = capacity

        self._job_recovery = job_recovery
        self._region = region
        self._zone = zone
        self._image_id = image_id
        self._disk_size = (_DEFAULT_DISK_SIZE_GB
                           if disk_size is None else int(disk_size))
        self._ports = list(ports) if ports else None
        self._labels = dict(labels) if labels else None
        self._accelerator_args = (dict(accelerator_args)
                                  if accelerator_args else None)
        if num_slices < 1:
            raise exceptions.InvalidTaskError(
                f'num_slices must be >= 1, got {num_slices}.')
        self._num_slices = int(num_slices)
        if _validate:
            self._try_validate()

    # ------------------------------------------------------------- parsing

    @staticmethod
    def _validate_count_str(
            field: str, value: Union[None, int, float, str]) -> Optional[str]:
        """'4' / '4.5' / '4+' grammar for cpus and memory requests."""
        if value is None:
            return None
        s = str(value).strip()
        import re  # pylint: disable=import-outside-toplevel
        if not re.fullmatch(r'\d+(\.\d+)?\+?', s):
            raise exceptions.InvalidTaskError(
                f'Invalid {field} request {value!r}: expected a number '
                "optionally followed by '+' (e.g. '4', '4+').")
        return s

    @staticmethod
    def _parse_accelerators(
            accelerators: Union[None, str, Dict[str, int]]
    ) -> Optional[Dict[str, int]]:
        """'A100:8' / 'tpu-v5e-16' / {'A100': 8} → canonical {name: count}."""
        if accelerators is None:
            return None
        if isinstance(accelerators, dict):
            items = list(accelerators.items())
        else:
            s = accelerators.strip()
            if ':' in s:
                name, _, count = s.partition(':')
                try:
                    items = [(name, int(count))]
                except ValueError as e:
                    raise exceptions.InvalidTaskError(
                        f'Invalid accelerator count in {s!r}.') from e
            else:
                items = [(s, 1)]
        if len(items) != 1:
            raise exceptions.InvalidTaskError(
                f'Exactly one accelerator type may be requested, '
                f'got {accelerators!r}.')
        name, count = items[0]
        canonical = accelerator_registry.canonicalize_accelerator_name(name)
        spec = accelerator_registry.parse_tpu_name(canonical)
        if spec is not None:
            if count not in (1, spec.num_chips):
                raise exceptions.InvalidTaskError(
                    f'TPU slices are atomic; request a larger slice type '
                    f'instead of {canonical}:{count}.')
            return {canonical: spec.num_chips}
        return {canonical: int(count)}

    # ---------------------------------------------------------- properties

    @property
    def cloud(self) -> Optional[cloud_lib.Cloud]:
        return self._cloud

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def accelerators(self) -> Optional[Dict[str, int]]:
        if self._accelerators is not None:
            return dict(self._accelerators)
        if self._cloud is not None and self._instance_type is not None:
            from skypilot_tpu import catalog  # pylint: disable=import-outside-toplevel
            return catalog.get_accelerators_from_instance_type(
                self._cloud.name, self._instance_type)
        return None

    @property
    def tpu_spec(self) -> Optional[accelerator_registry.TpuSliceSpec]:
        if self._accelerators is None:
            return None
        name = next(iter(self._accelerators))
        return accelerator_registry.parse_tpu_name(name)

    @property
    def cpus(self) -> Optional[str]:
        return self._cpus

    @property
    def memory(self) -> Optional[str]:
        return self._memory

    @property
    def use_spot(self) -> bool:
        return self._capacity is cloud_lib.ProvisionMode.SPOT

    @property
    def provision_mode(self) -> cloud_lib.ProvisionMode:
        return self._capacity

    @property
    def job_recovery(self) -> Optional[str]:
        return self._job_recovery

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    @property
    def disk_size(self) -> int:
        return self._disk_size

    @property
    def ports(self) -> Optional[List[int]]:
        return list(self._ports) if self._ports else None

    @property
    def labels(self) -> Optional[Dict[str, str]]:
        return dict(self._labels) if self._labels else None

    @property
    def accelerator_args(self) -> Optional[Dict[str, Any]]:
        return dict(self._accelerator_args) if self._accelerator_args else None

    @property
    def num_slices(self) -> int:
        return self._num_slices

    @property
    def num_hosts(self) -> int:
        """Hosts per slice-cluster: the gang width of one launch unit."""
        spec = self.tpu_spec
        if spec is None:
            return 1
        return spec.num_hosts * self._num_slices

    def is_launchable(self) -> bool:
        return self._cloud is not None and (self._instance_type is not None or
                                            self.tpu_spec is not None)

    # ---------------------------------------------------------- validation

    def _try_validate(self) -> None:
        if self._region is not None or self._zone is not None:
            if self._cloud is not None:
                self._region, self._zone = self._cloud.validate_region_zone(
                    self._region, self._zone)
        spec = self.tpu_spec
        if spec is not None:
            if self._instance_type is not None:
                raise exceptions.InvalidTaskError(
                    'TPU requests must not set instance_type (the slice is '
                    f'the unit): got {self._instance_type!r}.')
            if self._capacity is cloud_lib.ProvisionMode.RESERVED:
                args = self._accelerator_args or {}
                if not args.get('reservation'):
                    raise exceptions.InvalidTaskError(
                        'capacity: reserved requires accelerator_args: '
                        '{reservation: <name>}.')
        elif self._num_slices != 1:
            raise exceptions.InvalidTaskError(
                'num_slices > 1 requires a TPU accelerator.')
        if (self._instance_type is not None and self._cloud is not None and
                self._cloud.HAS_CATALOG):
            from skypilot_tpu import catalog  # pylint: disable=import-outside-toplevel
            if not catalog.instance_type_exists(self._cloud.name,
                                                self._instance_type):
                raise exceptions.InvalidTaskError(
                    f'Instance type {self._instance_type!r} not in the '
                    f'{self._cloud.name} catalog.')

    def get_required_cloud_features(
            self) -> Set[cloud_lib.CloudImplementationFeatures]:
        features: Set[cloud_lib.CloudImplementationFeatures] = set()
        if self.use_spot:
            features.add(cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE)
        if self._capacity is cloud_lib.ProvisionMode.QUEUED:
            features.add(cloud_lib.CloudImplementationFeatures.QUEUED_RESOURCE)
        if self._capacity is cloud_lib.ProvisionMode.RESERVED:
            features.add(cloud_lib.CloudImplementationFeatures.RESERVATION)
        if self.tpu_spec is not None:
            features.add(cloud_lib.CloudImplementationFeatures.TPU)
        if self._image_id is not None:
            features.add(cloud_lib.CloudImplementationFeatures.IMAGE_ID)
        if self._ports:
            features.add(cloud_lib.CloudImplementationFeatures.OPEN_PORTS)
        return features

    # ---------------------------------------------------------------- cost

    def get_cost(self, seconds: float) -> float:
        """USD for running this (launchable) resource for `seconds`."""
        if self._cloud is None:
            raise ValueError('Cost requires a concrete cloud.')
        hours = seconds / 3600.0
        cost = 0.0
        if self._instance_type is not None:
            cost += self._cloud.instance_type_to_hourly_cost(
                self._instance_type, self.use_spot, self._region, self._zone)
        if self._accelerators is not None:
            cost += self._cloud.accelerators_to_hourly_cost(
                self._accelerators, self.use_spot, self._region, self._zone)
        return cost * hours * self._num_slices

    # ---------------------------------------------------------------- copy

    def copy(self, **override: Any) -> 'Resources':
        fields: Dict[str, Any] = {
            'cloud': self._cloud,
            'instance_type': self._instance_type,
            'accelerators': self._accelerators,
            'cpus': self._cpus,
            'memory': self._memory,
            'capacity': self._capacity,
            'job_recovery': self._job_recovery,
            'region': self._region,
            'zone': self._zone,
            'image_id': self._image_id,
            'disk_size': self._disk_size,
            'ports': self._ports,
            'labels': self._labels,
            'accelerator_args': self._accelerator_args,
            'num_slices': self._num_slices,
        }
        fields.update(override)
        return Resources(**fields)

    # -------------------------------------------------------------- reuse

    def less_demanding_than(self, other: 'Resources') -> bool:
        """Can a task wanting `self` run on a cluster launched as `other`?

        Parity: reference resources.py:1104 — used by the cluster-reuse
        check in the backend.
        """
        if self._cloud is not None and self._cloud != other._cloud:
            return False
        if (self._region is not None and other._region is not None and
                self._region != other._region):
            return False
        if (self._zone is not None and other._zone is not None and
                self._zone != other._zone):
            return False
        if self.use_spot != other.use_spot:
            return False
        if (self._instance_type is not None and
                self._instance_type != other._instance_type):
            return False
        mine = self._accelerators
        if mine is not None:
            theirs = other.accelerators or {}
            for name, count in mine.items():
                if theirs.get(name, 0) < count:
                    return False
        if self._num_slices > other._num_slices:
            return False
        return True

    # ---------------------------------------------------------------- yaml

    @classmethod
    def from_yaml_config(cls, config: Optional[Dict[str, Any]]) -> 'Resources':
        if config is None:
            return cls()
        config = dict(config)
        # Reference-familiar aliases (sky YAML: infra/capacity_type/
        # spot_recovery) normalize onto the canonical field names.
        for alias, canonical in (('infra', 'cloud'),
                                 ('capacity_type', 'capacity'),
                                 ('spot_recovery', 'job_recovery')):
            if alias in config:
                if canonical in config:
                    raise exceptions.InvalidTaskError(
                        f'Give either {alias!r} or {canonical!r}, '
                        'not both.')
                config[canonical] = config.pop(alias)
        # TPU slice details ride in accelerator_args; the flat spelling
        # is accepted and folded in.
        flat_args = {k: config.pop(k)
                     for k in ('topology', 'runtime_version', 'reservation')
                     if k in config}
        if flat_args:
            merged = dict(config.get('accelerator_args') or {})
            dup = set(flat_args) & set(merged)
            if dup:
                raise exceptions.InvalidTaskError(
                    f'{sorted(dup)} given both top-level and inside '
                    'accelerator_args; give each once.')
            merged.update(flat_args)
            config['accelerator_args'] = merged
        known = {
            'cloud', 'instance_type', 'accelerators', 'cpus', 'memory',
            'use_spot', 'capacity', 'job_recovery', 'region', 'zone',
            'image_id', 'disk_size', 'ports', 'labels', 'accelerator_args',
            'num_slices',
        }
        unknown = set(config) - known
        if unknown:
            raise exceptions.InvalidTaskError(
                f'Unknown resources fields: {sorted(unknown)}')
        ports = config.get('ports')
        if isinstance(ports, (int, str)):
            ports = [int(ports)]
        elif ports is not None:
            ports = [int(p) for p in ports]
        config['ports'] = ports
        return cls(**config)

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}
        if self._cloud is not None:
            config['cloud'] = self._cloud.name
        if self._instance_type is not None:
            config['instance_type'] = self._instance_type
        if self._accelerators is not None:
            name, count = next(iter(self._accelerators.items()))
            spec = accelerator_registry.parse_tpu_name(name)
            config['accelerators'] = (name if spec is not None else
                                      f'{name}:{count}')
        for key, value in (
            ('cpus', self._cpus),
            ('memory', self._memory),
            ('job_recovery', self._job_recovery),
            ('region', self._region),
            ('zone', self._zone),
            ('image_id', self._image_id),
            ('ports', self._ports),
            ('labels', self._labels),
            ('accelerator_args', self._accelerator_args),
        ):
            if value is not None:
                config[key] = value
        if self._capacity is not cloud_lib.ProvisionMode.ON_DEMAND:
            config['capacity'] = self._capacity.value
        if self._disk_size != _DEFAULT_DISK_SIZE_GB:
            config['disk_size'] = self._disk_size
        if self._num_slices != 1:
            config['num_slices'] = self._num_slices
        return config

    # ---------------------------------------------------------------- repr

    def __repr__(self) -> str:
        parts = []
        if self._cloud is not None:
            parts.append(str(self._cloud))
        if self._instance_type is not None:
            parts.append(self._instance_type)
        if self._accelerators is not None:
            name, count = next(iter(self._accelerators.items()))
            spec = accelerator_registry.parse_tpu_name(name)
            if spec is not None:
                label = name
                if self._num_slices > 1:
                    label += f'×{self._num_slices}'
                parts.append(label)
            else:
                parts.append(f'{name}:{count}')
        if self._cpus:
            parts.append(f'cpus={self._cpus}')
        if self._memory:
            parts.append(f'mem={self._memory}')
        if self.use_spot:
            parts.append('[spot]')
        elif self._capacity not in (None, cloud_lib.ProvisionMode.ON_DEMAND):
            parts.append(f'[{self._capacity.value}]')
        if self._region:
            parts.append(f'region={self._region}')
        if self._zone:
            parts.append(f'zone={self._zone}')
        return '<Resources: ' + ' '.join(parts or ['(empty)']) + '>'

    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        import json  # pylint: disable=import-outside-toplevel
        # sort_keys canonicalizes nested dicts (labels, accelerator_args) so
        # hash agrees with __eq__ regardless of insertion order.
        return hash(json.dumps(self.to_yaml_config(), sort_keys=True,
                               default=str))
