"""Pass framework: findings, suppressions, baseline, the runner.

A checker pass is a :class:`Pass` subclass declaring the rule ids it
owns and yielding :class:`Finding`s from one shared
:class:`~skypilot_tpu.analysis.index.PackageIndex`.  The runner then:

1. drops findings covered by an inline suppression —
   ``# skytpu: lint-ok[rule] reason=...`` on the finding's line (or a
   comment-only line directly above it).  The reason is MANDATORY: a
   reasonless suppression does not suppress and is itself a
   `suppression-invalid` finding.
2. drops findings recorded in the committed baseline
   (`lint-baseline.json`: grandfathered findings keyed by
   ``rule//file//message`` — line numbers drift, messages don't), and
   flags baseline entries that no longer reproduce as
   `baseline-stale` findings so the baseline can only shrink.
3. sorts everything by (file, line, rule, message) so two runs over
   the same tree are byte-identical (`--json` is diffable and the
   determinism test pins it).

Exit contract (the CLI and tier-1 test): unsuppressed findings -> 1.
"""
from __future__ import annotations

import dataclasses
import json
import pathlib
import time
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

from skypilot_tpu.analysis import index as index_lib

BASELINE_FILENAME = 'lint-baseline.json'

# Rules owned by the framework itself (not any pass).
RULE_SUPPRESSION_INVALID = 'suppression-invalid'
RULE_BASELINE_STALE = 'baseline-stale'


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    file: str          # package-relative, e.g. 'serve/router.py'
    line: int
    message: str

    def key(self) -> str:
        """Baseline identity: stable across unrelated line drift."""
        return f'{self.rule}//{self.file}//{self.message}'

    def as_dict(self) -> Dict[str, object]:
        return {'rule': self.rule, 'file': self.file,
                'line': self.line, 'message': self.message}

    def render(self) -> str:
        return f'{self.file}:{self.line}: [{self.rule}] {self.message}'


class Pass:
    """One checker.  Subclasses set `name`, `rules`, `description` and
    implement :meth:`run`."""

    name: str = ''
    rules: Sequence[str] = ()
    description: str = ''

    def run(self, idx: index_lib.PackageIndex) -> Iterator[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]            # unsuppressed: these fail lint
    suppressed: List[Finding]          # silenced by an inline lint-ok
    baselined: List[Finding]           # silenced by the baseline file
    duration_s: float
    passes: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> str:
        """Deterministic (two runs over one tree are byte-identical:
        no timestamps, stable sort everywhere)."""
        payload = {
            'version': 1,
            'ok': self.ok,
            'passes': list(self.passes),
            'findings': [f.as_dict() for f in self.findings],
            'suppressed': [f.as_dict() for f in self.suppressed],
            'baselined': [f.as_dict() for f in self.baselined],
        }
        return json.dumps(payload, indent=2, sort_keys=True)


def _sort(findings: Iterable[Finding]) -> List[Finding]:
    return sorted(findings,
                  key=lambda f: (f.file, f.line, f.rule, f.message))


def load_baseline(path: Optional[pathlib.Path]) -> List[str]:
    """Baseline keys from `lint-baseline.json` (absent file = empty)."""
    if path is None or not path.is_file():
        return []
    data = json.loads(path.read_text(encoding='utf-8'))
    return [str(k) for k in data.get('findings', [])]


def write_baseline(path: pathlib.Path,
                   findings: Iterable[Finding]) -> None:
    """Grandfather the given findings (the `--update-baseline`
    workflow: commit the shrinking file, never grow it by hand)."""
    payload = {'version': 1,
               'findings': sorted(f.key() for f in findings)}
    path.write_text(json.dumps(payload, indent=2) + '\n',
                    encoding='utf-8')


def default_passes() -> List[Pass]:
    from skypilot_tpu.analysis import passes as passes_lib  # pylint: disable=import-outside-toplevel
    return passes_lib.all_passes()


def rule_catalog(passes: Optional[Sequence[Pass]] = None) \
        -> Dict[str, str]:
    """rule id -> owning pass name (plus the framework's own rules)."""
    catalog = {RULE_SUPPRESSION_INVALID: 'framework',
               RULE_BASELINE_STALE: 'framework'}
    for p in (default_passes() if passes is None else passes):
        for rule in p.rules:
            catalog[rule] = p.name
    return catalog


def run_lint(idx: index_lib.PackageIndex,
             passes: Optional[Sequence[Pass]] = None,
             rules: Optional[Sequence[str]] = None,
             baseline_path: Optional[pathlib.Path] = None) \
        -> LintResult:
    """Run the pass suite over one index.

    `rules` filters which rule ids may report (passes owning none of
    the requested rules are skipped entirely).  The framework rules
    (`suppression-invalid`, `baseline-stale`) always run: a filter
    must not hide a broken suppression or a stale baseline.
    """
    t0 = time.perf_counter()
    if passes is None:
        passes = default_passes()
    wanted = set(rules) if rules else None
    known = set(rule_catalog(passes))
    if wanted is not None:
        unknown = sorted(wanted - known)
        if unknown:
            raise ValueError(
                f'unknown rule id(s) {unknown}; have {sorted(known)}')
    raw: List[Finding] = []
    ran: List[str] = []
    ran_rules: set = set()
    for p in passes:
        if wanted is not None and not wanted.intersection(p.rules):
            continue
        ran.append(p.name)
        ran_rules.update(p.rules if wanted is None
                         else wanted.intersection(p.rules))
        for f in p.run(idx):
            if wanted is not None and f.rule not in wanted:
                continue
            raw.append(f)

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    seen_sups: set = set()
    for f in _sort(raw):
        mod = idx.modules.get(f.file)
        sup = mod.suppression_for(f.line, f.rule) if mod else None
        if sup is not None and sup.reason:
            suppressed.append(f)
        elif sup is not None:
            # Matching suppression but no reason: the finding stands
            # AND the suppression itself is flagged (once per line).
            findings.append(f)
            if (f.file, sup.line) not in seen_sups:
                seen_sups.add((f.file, sup.line))
                findings.append(Finding(
                    RULE_SUPPRESSION_INVALID, f.file, sup.line,
                    'lint-ok suppression without a reason= — the '
                    'reason is mandatory'))
        else:
            findings.append(f)

    # Reasonless suppressions that matched NO finding still get
    # flagged: they are dead weight waiting to silently eat a future
    # finding without justification.
    for rel, mod in sorted(idx.modules.items()):
        for sup in mod.suppressions:
            if not sup.reason and (rel, sup.line) not in seen_sups:
                seen_sups.add((rel, sup.line))
                findings.append(Finding(
                    RULE_SUPPRESSION_INVALID, rel, sup.line,
                    'lint-ok suppression without a reason= — the '
                    'reason is mandatory'))

    baseline = set(load_baseline(baseline_path))
    if baseline:
        baselined = [f for f in findings if f.key() in baseline]
        matched = {f.key() for f in baselined}
        findings = [f for f in findings if f.key() not in baseline]
        for key in sorted(baseline - matched):
            rule, file, _ = (key.split('//', 2) + ['', ''])[:3]
            if rule not in ran_rules:
                # Its pass did not run (a --rule filter): absence of
                # the finding proves nothing about staleness.
                continue
            findings.append(Finding(
                RULE_BASELINE_STALE, file or '<baseline>', 0,
                f'baselined finding no longer reproduces — remove it '
                f'from {BASELINE_FILENAME}: {key}'))
    else:
        baselined = []

    return LintResult(findings=_sort(findings),
                      suppressed=_sort(suppressed),
                      baselined=_sort(baselined),
                      duration_s=time.perf_counter() - t0,
                      passes=ran)
