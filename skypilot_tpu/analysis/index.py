"""Parse-once package index the checker passes share.

The three pre-existing ad-hoc lints each re-walked the whole package
with their own `ast.parse` loop; every new pass would have added
another.  This module parses each module ONCE and exposes the derived
tables every pass needs:

- per-module: the AST, raw source lines, import-alias map
  (``name -> dotted module``, resolving relative imports inside the
  package), and the inline-suppression table (`core.py` consumes it).
- per-class: attribute assignment sites (``self.X = ...``) and which
  attributes hold `threading` locks.
- per-function: a qualname table (``module::Class.method`` /
  ``module::func``) with the raw nodes, for the call-graph passes.

Everything here is `ast`-only — building an index never imports an
analyzed module, so linting cannot execute package code.
"""
from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
from typing import Dict, Iterator, List, Optional, Tuple

# ``# skytpu: lint-ok[rule-a,rule-b] reason=...`` — reason mandatory
# (enforced by core.py; an empty reason is a `suppression-invalid`
# finding, and the suppression does NOT apply).
_SUPPRESS_RE = re.compile(
    r'#\s*skytpu:\s*lint-ok\[([a-z0-9_,\- ]*)\]\s*(?:reason=(.*))?$')

_LOCK_FACTORIES = ('Lock', 'RLock', 'Condition', 'Semaphore',
                   'BoundedSemaphore')


@dataclasses.dataclass
class Suppression:
    line: int            # line the suppression comment sits on
    applies_to: int      # line whose findings it suppresses
    rules: Tuple[str, ...]
    reason: str          # '' = invalid (reason is mandatory)


@dataclasses.dataclass
class ClassInfo:
    module: str                      # rel path, e.g. 'serve/router.py'
    name: str
    node: ast.ClassDef
    # attr -> [(method_name, lineno)] for every ``self.attr = ...``.
    attr_writes: Dict[str, List[Tuple[str, int]]]
    # attrs assigned a threading.Lock()/RLock()/Condition()/... value.
    lock_attrs: Tuple[str, ...]


@dataclasses.dataclass
class FunctionInfo:
    module: str
    qualname: str                    # 'Class.method' or 'func'
    node: ast.AST                    # FunctionDef / AsyncFunctionDef


@dataclasses.dataclass
class ModuleInfo:
    rel: str                         # path relative to the package root
    path: pathlib.Path
    tree: ast.Module
    lines: List[str]
    # local name -> dotted module target ('np' -> 'numpy',
    # 'scheduler' -> 'skypilot_tpu.serve.scheduler').
    import_aliases: Dict[str, str]
    # local name -> (dotted module, attr) for `from m import a [as b]`.
    from_imports: Dict[str, Tuple[str, str]]
    suppressions: List[Suppression]

    def suppression_for(self, line: int, rule: str) \
            -> Optional[Suppression]:
        for sup in self.suppressions:
            if sup.applies_to == line and (not sup.rules or
                                           rule in sup.rules):
                return sup
        return None


def _parse_suppressions(lines: List[str]) -> List[Suppression]:
    sups: List[Suppression] = []
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(',')
                      if r.strip())
        reason = (m.group(2) or '').strip()
        # A comment-only line suppresses the next non-comment line;
        # a trailing comment suppresses its own line.
        if raw.strip().startswith('#'):
            applies = i + 1
            for j in range(i, len(lines)):
                if not lines[j].strip().startswith('#'):
                    applies = j + 1
                    break
        else:
            applies = i
        sups.append(Suppression(line=i, applies_to=applies,
                                rules=rules, reason=reason))
    return sups


def _resolve_relative(package: str, rel: str, module: Optional[str],
                      level: int) -> Optional[str]:
    """Dotted target of a `from ... import` seen in module `rel`."""
    if level == 0:
        return module
    parts = (package + '/' + rel).split('/')[:-1]  # containing package
    up = level - 1
    if up > len(parts):
        return None
    base = parts[:len(parts) - up]
    dotted = '.'.join(base)
    if module:
        dotted = f'{dotted}.{module}' if dotted else module
    return dotted or None


def _is_lock_factory(value: ast.AST) -> bool:
    """``threading.Lock()`` / ``Lock()`` / ``threading.Condition(...)``."""
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    if isinstance(func, ast.Attribute):
        return (isinstance(func.value, ast.Name) and
                func.value.id == 'threading' and
                func.attr in _LOCK_FACTORIES)
    if isinstance(func, ast.Name):
        return func.id in _LOCK_FACTORIES
    return False


def _class_info(rel: str, node: ast.ClassDef) -> ClassInfo:
    attr_writes: Dict[str, List[Tuple[str, int]]] = {}
    lock_attrs: List[str] = []
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        method = item.name
        for sub in ast.walk(item):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
                value = getattr(sub, 'value', None)
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute) and
                        isinstance(tgt.value, ast.Name) and
                        tgt.value.id == 'self'):
                    attr_writes.setdefault(tgt.attr, []).append(
                        (method, sub.lineno))
                    if value is not None and _is_lock_factory(value):
                        if tgt.attr not in lock_attrs:
                            lock_attrs.append(tgt.attr)
    return ClassInfo(module=rel, name=node.name, node=node,
                     attr_writes=attr_writes,
                     lock_attrs=tuple(lock_attrs))


class PackageIndex:
    """All modules of one package, parsed once."""

    def __init__(self, root: pathlib.Path,
                 package: Optional[str] = None) -> None:
        self.root = pathlib.Path(root)
        self.package = package or self.root.name
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        for path in sorted(self.root.rglob('*.py')):
            rel = path.relative_to(self.root).as_posix()
            if '__pycache__' in rel:
                continue
            self._add_module(rel, path)

    # ----------------------------------------------------------- build

    def _add_module(self, rel: str, path: pathlib.Path) -> None:
        source = path.read_text(encoding='utf-8')
        tree = ast.parse(source, filename=str(path))
        lines = source.splitlines()
        aliases: Dict[str, str] = {}
        from_imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        top = a.name.split('.')[0]
                        aliases[top] = top
            elif isinstance(node, ast.ImportFrom):
                target = _resolve_relative(self.package, rel,
                                           node.module, node.level)
                if target is None:
                    continue
                for a in node.names:
                    if a.name == '*':
                        continue
                    local = a.asname or a.name
                    from_imports[local] = (target, a.name)
        self.modules[rel] = ModuleInfo(
            rel=rel, path=path, tree=tree, lines=lines,
            import_aliases=aliases, from_imports=from_imports,
            suppressions=_parse_suppressions(lines))
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                info = _class_info(rel, node)
                self.classes[(rel, node.name)] = info
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qual = f'{node.name}.{item.name}'
                        self.functions[(rel, qual)] = FunctionInfo(
                            rel, qual, item)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.functions[(rel, node.name)] = FunctionInfo(
                    rel, node.name, node)

    # --------------------------------------------------------- queries

    def _dotted_to_rel(self, dotted: str) -> Optional[str]:
        """'skypilot_tpu.serve.router' -> 'serve/router.py' (None when
        the dotted path is not a module of this package)."""
        prefix = self.package + '.'
        if dotted == self.package:
            inner = ''
        elif dotted.startswith(prefix):
            inner = dotted[len(prefix):].replace('.', '/')
        else:
            return None
        for cand in (f'{inner}.py' if inner else '__init__.py',
                     f'{inner}/__init__.py' if inner else '__init__.py'):
            if cand in self.modules:
                return cand
        return None

    def resolve_module_alias(self, rel: str, name: str) \
            -> Optional[str]:
        """Local `name` in module `rel` -> rel path of the package
        module it aliases (None for stdlib / third-party)."""
        mod = self.modules.get(rel)
        if mod is None:
            return None
        dotted = mod.import_aliases.get(name)
        if dotted is not None:
            return self._dotted_to_rel(dotted)
        # `from skypilot_tpu.serve import scheduler` binds a MODULE —
        # resolved lazily (at parse time the target module may not be
        # in the index yet).
        from_import = mod.from_imports.get(name)
        if from_import is not None:
            return self._dotted_to_rel(
                f'{from_import[0]}.{from_import[1]}')
        return None

    def iter_calls(self, tree: ast.AST) -> Iterator[ast.Call]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                yield node

    def callee_name(self, call: ast.Call) -> Optional[str]:
        """Trailing name of the called expression ('append' for
        `x.y.append(...)`, 'jit' for `jit(...)`)."""
        func = call.func
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute):
            return func.attr
        return None
