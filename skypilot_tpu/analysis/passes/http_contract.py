"""`http-*`: the cross-process HTTP contracts hold statically.

The serving fleet is a multi-process system: two replica HTTP fronts
(serve/model_server.py threaded, serve/async_server.py asyncio), the
load balancer's `/lb/` control plane, and the controller's
`/controller/` endpoint — plus a dozen in-package clients (the LB's
handoff legs, the controller's probes, the CLI scrapers, the trace
assembler).  Nothing at runtime checks that a client's path still hits
a registered route or that a header a server reads is still stamped by
anyone; this pass derives both sides from the ASTs and cross-checks:

- **routes** — a server module registers a route wherever it compares
  a path-ish expression against a string literal (or a
  serve/http_protocol.py constant): `self.path == GENERATE`,
  `path in _ROUTABLE_PATHS`.  Client call sites (`requests.get/post`,
  the LB's `_http_request`/`_json_request`, urlopen) contribute the
  trailing path of their URL argument (literal, `url + CONST`,
  f-string, or a local conditional between constants).  Namespaces
  split by prefix: `/lb/` -> the LB, `/controller/` -> the
  controller, everything else -> the replica fronts.
- **headers** — `X-SkyTPU-*` reads (`headers.get(...)` in a server
  module) vs stamps (any other use of the header constant anywhere).
- **status codes** — int literals a client branches on
  (`status == 429`, `status in (400, 404)`) must be emittable by some
  server (`_reply(429, ...)`, `send_response(code)`,
  `_HttpError(503, ...)`, ...).

Rules:

- `http-front-parity` — the threaded and async replica fronts must
  expose the identical route surface and read the identical header
  set (threaded/async drift is exactly what nothing else tests).
- `http-unknown-route` — a client path no server registers.
- `http-header-unstamped` — a server reads a header nothing stamps.
- `http-header-unread` — a canonical header no server module reads.
- `http-raw-literal` — a raw `X-SkyTPU-*` or canonical-path string
  literal outside serve/http_protocol.py (use the constants; the
  module exists so the contract has one home).
- `http-status-unemittable` — a client equality/membership branch on
  a status code no server can emit.
- `http-doc-drift` — the `### HTTP API` table in docs/serving.md
  must list exactly the registered routes, both directions.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib
from skypilot_tpu.analysis.passes import metrics_catalog

PROTOCOL_MODULE = 'serve/http_protocol.py'
REPLICA_FRONTS = ('serve/model_server.py', 'serve/async_server.py')
SERVER_MODULES = REPLICA_FRONTS + ('serve/load_balancer.py',
                                   'serve/controller.py')
# Where header constants are DEFINED (module-level assignments there
# are neither reads nor stamps).
_HEADER_HOMES = (PROTOCOL_MODULE, 'serve/router.py',
                 'observability/tracing.py')

_HEADER_RE = re.compile(r'^X-SkyTPU-')
_CLIENT_CALLEES = {'get', 'post', 'urlopen', 'request'}
_CLIENT_PATH_ARG = {'_http_request': 1, '_json_request': 1}
_REPLY_CALLEES = {'_reply', '_json', '_json_response', 'send_response',
                  '_simple_response', '_HttpError'}

# Namespace prefixes (the one place the pass itself needs the raw
# strings: it classifies client paths before knowing the route sets).
# skytpu: lint-ok[http-raw-literal] reason=the pass that enforces the ban needs the LB namespace prefix to classify client paths
_LB_PREFIX = '/lb/'
# skytpu: lint-ok[http-raw-literal] reason=the pass that enforces the ban needs the controller namespace prefix to classify client paths
_CONTROLLER_PREFIX = '/controller/'

_DOC = 'serving.md'
_SECTION = '### HTTP API'
_DOC_PATH_RE = re.compile(r'`(/[a-z_/]*)`')


# ------------------------------------------------------------ resolution


class _Resolver:
    """Constant-string resolution through module-level assignments and
    cross-module imports (the http_protocol constants)."""

    def __init__(self, idx: index_lib.PackageIndex) -> None:
        self.idx = idx
        self.consts: Dict[Tuple[str, str], ast.AST] = {}
        for rel, mod in idx.modules.items():
            for node in mod.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.consts[(rel, tgt.id)] = node.value

    def resolve_str(self, rel: str, expr: ast.AST,
                    depth: int = 0) -> Optional[str]:
        """expr -> string value (constants, names, attributes)."""
        if depth > 8 or expr is None:
            return None
        if isinstance(expr, ast.Constant) and \
                isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute) and \
                expr.func.attr == 'lower' and not expr.args:
            # HEADER.lower() — async fronts keep lower-cased header
            # maps; the canonical name is what the contract compares.
            return self.resolve_str(rel, expr.func.value, depth + 1)
        if isinstance(expr, ast.Name):
            target = self.consts.get((rel, expr.id))
            if target is not None:
                return self.resolve_str(rel, target, depth + 1)
            mod = self.idx.modules.get(rel)
            if mod is not None and expr.id in mod.from_imports:
                trel = self.idx._dotted_to_rel(  # pylint: disable=protected-access
                    mod.from_imports[expr.id][0])
                name = mod.from_imports[expr.id][1]
                if trel is not None:
                    return self.resolve_str(
                        trel, self.consts.get((trel, name)), depth + 1)
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            target_rel = self.idx.resolve_module_alias(
                rel, expr.value.id)
            if target_rel is not None:
                return self.resolve_str(
                    target_rel,
                    self.consts.get((target_rel, expr.attr)),
                    depth + 1)
        return None

    def resolve_str_list(self, rel: str, expr: ast.AST,
                         depth: int = 0) -> List[str]:
        """Strings of a tuple/list-ish constant expression."""
        if depth > 8 or expr is None:
            return []
        one = self.resolve_str(rel, expr, depth)
        if one is not None:
            return [one]
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: List[str] = []
            for elt in expr.elts:
                out.extend(self.resolve_str_list(rel, elt, depth + 1))
            return out
        if isinstance(expr, ast.Name):
            target = self.consts.get((rel, expr.id))
            if target is not None:
                return self.resolve_str_list(rel, target, depth + 1)
            mod = self.idx.modules.get(rel)
            if mod is not None and expr.id in mod.from_imports:
                trel = self.idx._dotted_to_rel(  # pylint: disable=protected-access
                    mod.from_imports[expr.id][0])
                name = mod.from_imports[expr.id][1]
                if trel is not None:
                    return self.resolve_str_list(
                        trel, self.consts.get((trel, name)), depth + 1)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            trel = self.idx.resolve_module_alias(rel, expr.value.id)
            if trel is not None:
                return self.resolve_str_list(
                    trel, self.consts.get((trel, expr.attr)),
                    depth + 1)
        return []


def _url_tail(value: str) -> Optional[str]:
    """Path component of a URL-ish string ('/x' stays, full URLs lose
    scheme+host, bare hosts have no path)."""
    if value.startswith('/'):
        return value
    if '://' in value:
        rest = value.split('://', 1)[1]
        if '/' in rest:
            return '/' + rest.split('/', 1)[1]
    return None


# ---------------------------------------------------------- extraction


def _docstring_ids(tree: ast.AST) -> Set[int]:
    ids: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, 'body', [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                ids.add(id(body[0].value))
    return ids


def server_routes(idx: index_lib.PackageIndex, res: _Resolver,
                  rel: str) -> Dict[str, int]:
    """path -> first registration line, from path comparisons in one
    server module."""
    mod = idx.modules.get(rel)
    if mod is None:
        return {}
    routes: Dict[str, int] = {}

    def record(path: str, line: int) -> None:
        if path.startswith('/') and len(path) > 1:
            routes.setdefault(path, line)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Compare):
            continue
        if not all(isinstance(op, (ast.Eq, ast.NotEq, ast.In))
                   for op in node.ops):
            continue
        sides = [node.left] + list(node.comparators)
        texts = []
        for side in sides:
            try:
                texts.append(ast.unparse(side).lower())
            except Exception:  # pylint: disable=broad-except
                texts.append('')
        if not any('path' in t for t in texts):
            continue
        for side in sides:
            for value in res.resolve_str_list(rel, side):
                record(value, node.lineno)
    return routes


def client_paths(idx: index_lib.PackageIndex, res: _Resolver) \
        -> List[Tuple[str, int, str]]:
    """(file, line, path) for every constant-resolvable client call."""
    out: List[Tuple[str, int, str]] = []
    markers = ('requests', 'urlopen', '_http_request', '_json_request')
    for rel, mod in sorted(idx.modules.items()):
        if rel == PROTOCOL_MODULE:
            continue
        text = '\n'.join(mod.lines)
        if not any(m in text for m in markers):
            continue
        for call in idx.iter_calls(mod.tree):
            callee = idx.callee_name(call)
            arg_i = None
            if callee in _CLIENT_PATH_ARG:
                arg_i = _CLIENT_PATH_ARG[callee]
            elif callee in _CLIENT_CALLEES and \
                    isinstance(call.func, ast.Attribute) and \
                    isinstance(call.func.value, ast.Name) and \
                    call.func.value.id in ('requests', 'urllib',
                                           'request'):
                arg_i = 0
            elif callee == 'urlopen':
                arg_i = 0
            if arg_i is None or len(call.args) <= arg_i:
                continue
            for path in _arg_paths(idx, res, rel, call.args[arg_i]):
                out.append((rel, call.lineno, path))
    return out


def _arg_paths(idx: index_lib.PackageIndex, res: _Resolver, rel: str,
               arg: ast.AST, depth: int = 0) -> List[str]:
    """Trailing path(s) of a URL argument expression."""
    if depth > 6:
        return []
    value = res.resolve_str(rel, arg)
    if value is not None:
        tail = _url_tail(value)
        return [tail] if tail else []
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        right = res.resolve_str(rel, arg.right)
        if right is not None and right.startswith('/'):
            return [right]
        return _arg_paths(idx, res, rel, arg.right, depth + 1)
    if isinstance(arg, ast.JoinedStr) and arg.values:
        last = arg.values[-1]
        if isinstance(last, ast.Constant) and \
                isinstance(last.value, str):
            tail = _url_tail(last.value)
            return [tail] if tail else []
        if isinstance(last, ast.FormattedValue):
            return _arg_paths(idx, res, rel, last.value, depth + 1)
    if isinstance(arg, ast.IfExp):
        return (_arg_paths(idx, res, rel, arg.body, depth + 1) +
                _arg_paths(idx, res, rel, arg.orelse, depth + 1))
    if isinstance(arg, ast.Call) and \
            idx.callee_name(arg) == 'rstrip' and \
            isinstance(arg.func, ast.Attribute):
        return []
    if isinstance(arg, ast.Name):
        # Function-local assignment (the aggregator's
        # `path = LB_METRICS if kind == 'lb' else METRICS`).
        fn = _enclosing_function(idx, rel, arg)
        if fn is not None:
            paths: List[str] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == arg.id
                        for t in node.targets):
                    paths.extend(_arg_paths(idx, res, rel, node.value,
                                            depth + 1))
            return paths
    return []


def _enclosing_function(idx: index_lib.PackageIndex, rel: str,
                        node: ast.AST) -> Optional[ast.AST]:
    for (frel, _), fn in idx.functions.items():
        if frel != rel:
            continue
        for sub in ast.walk(fn.node):
            if sub is node:
                return fn.node
    return None


def header_reads(idx: index_lib.PackageIndex, res: _Resolver,
                 rel: str) -> Dict[str, int]:
    """header -> first read line: `<headers-ish>.get(HEADER)` calls."""
    mod = idx.modules.get(rel)
    if mod is None:
        return {}
    reads: Dict[str, int] = {}
    for call in idx.iter_calls(mod.tree):
        if idx.callee_name(call) != 'get' or not call.args:
            continue
        func = call.func
        if not isinstance(func, ast.Attribute):
            continue
        try:
            recv = ast.unparse(func.value).lower()
        except Exception:  # pylint: disable=broad-except
            continue
        if 'headers' not in recv:
            continue
        arg = call.args[0]
        # HEADER or HEADER.lower()
        if isinstance(arg, ast.Call) and \
                idx.callee_name(arg) == 'lower' and \
                isinstance(arg.func, ast.Attribute):
            arg = arg.func.value
        value = res.resolve_str(rel, arg)
        if value is not None and _HEADER_RE.match(value):
            reads.setdefault(value, call.lineno)
    return reads


def _read_arg_ids(idx: index_lib.PackageIndex, rel: str) -> Set[int]:
    """Node ids used as header-read `.get()` arguments (excluded from
    the stamp scan)."""
    mod = idx.modules.get(rel)
    ids: Set[int] = set()
    if mod is None:
        return ids
    for call in idx.iter_calls(mod.tree):
        if idx.callee_name(call) != 'get' or not call.args:
            continue
        func = call.func
        if not isinstance(func, ast.Attribute):
            continue
        arg = call.args[0]
        ids.update(id(n) for n in ast.walk(arg))
    return ids


def header_stamps(idx: index_lib.PackageIndex,
                  res: _Resolver) -> Dict[str, int]:
    """header -> stamp count: any resolvable reference to an
    X-SkyTPU-* constant that is not a read key or a definition."""
    stamps: Dict[str, int] = {}
    for rel, mod in sorted(idx.modules.items()):
        text = '\n'.join(mod.lines)
        if 'X-SkyTPU' not in text and '_HEADER' not in text:
            continue
        defs: Set[int] = set()
        if rel in _HEADER_HOMES:
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    defs.update(id(n) for n in ast.walk(node))
        read_ids = _read_arg_ids(idx, rel)
        for node in ast.walk(mod.tree):
            if id(node) in defs or id(node) in read_ids:
                continue
            # Cheap prefilter before constant resolution: header
            # references are X-SkyTPU-* literals or *_HEADER names.
            if isinstance(node, ast.Constant):
                if not (isinstance(node.value, str) and
                        _HEADER_RE.match(node.value)):
                    continue
            elif isinstance(node, ast.Name):
                if not node.id.endswith('_HEADER'):
                    continue
            elif isinstance(node, ast.Attribute):
                if not (isinstance(node.value, ast.Name) and
                        node.attr.endswith('_HEADER')):
                    continue
            else:
                continue
            value = res.resolve_str(rel, node)
            if value is not None and _HEADER_RE.match(value):
                stamps[value] = stamps.get(value, 0) + 1
    return stamps


def emitted_statuses(idx: index_lib.PackageIndex,
                     res: _Resolver) -> Set[int]:
    """Status codes any server module can emit."""
    codes: Set[int] = set()
    for rel in SERVER_MODULES:
        mod = idx.modules.get(rel)
        if mod is None:
            continue
        for (frel, _), fn in sorted(idx.functions.items()):
            if frel != rel:
                continue
            local_ints: Dict[str, List[int]] = {}
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, int):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_ints.setdefault(tgt.id, []).append(
                                node.value.value)
            for call in idx.iter_calls(fn.node):
                if idx.callee_name(call) not in _REPLY_CALLEES or \
                        not call.args:
                    continue
                arg = call.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, int):
                    codes.add(arg.value)
                elif isinstance(arg, ast.Name):
                    codes.update(local_ints.get(arg.id, []))
    return codes


def client_status_branches(idx: index_lib.PackageIndex) \
        -> List[Tuple[str, int, int]]:
    """(file, line, code) for client-side `status ==`/`in` branches."""
    out: List[Tuple[str, int, int]] = []
    for rel, mod in sorted(idx.modules.items()):
        if not any('status' in line for line in mod.lines):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not all(isinstance(op, (ast.Eq, ast.NotEq, ast.In))
                       for op in node.ops):
                continue
            try:
                left = ast.unparse(node.left).lower()
            except Exception:  # pylint: disable=broad-except
                continue
            if 'status' not in left:
                continue
            for comp in node.comparators:
                elts = (comp.elts if isinstance(comp, (ast.Tuple,
                                                       ast.List))
                        else [comp])
                for elt in elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, int):
                        out.append((rel, node.lineno, elt.value))
    return out


def documented_routes(doc_dir) -> Set[str]:
    doc_path = doc_dir / _DOC
    if not doc_path.is_file():
        return set()
    doc = doc_path.read_text(encoding='utf-8')
    in_section = False
    paths: Set[str] = set()
    for line in doc.splitlines():
        if line.startswith('#'):
            in_section = line.strip() == _SECTION
            continue
        if in_section and line.startswith('|'):
            cells = line.split('|')
            if len(cells) >= 2:
                paths.update(_DOC_PATH_RE.findall(cells[1]))
    return paths


# ---------------------------------------------------------------- pass


class HttpContractPass(core.Pass):

    name = 'http-contract'
    rules = ('http-front-parity', 'http-unknown-route',
             'http-header-unstamped', 'http-header-unread',
             'http-raw-literal', 'http-status-unemittable',
             'http-doc-drift')
    description = ('client call sites match registered routes; the '
                   'two replica fronts expose identical surfaces; '
                   'headers read are stamped; status codes branched '
                   'on are emittable; raw protocol literals live in '
                   'serve/http_protocol.py only')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        if PROTOCOL_MODULE not in idx.modules:
            return
        res = _Resolver(idx)
        canonical = self._canonical(idx)
        headers = {v for v in canonical if _HEADER_RE.match(v)}
        paths = {v for v in canonical if v.startswith('/')}

        front_routes = {rel: server_routes(idx, res, rel)
                        for rel in REPLICA_FRONTS}
        lb_routes = {p: line for p, line in server_routes(
            idx, res, 'serve/load_balancer.py').items()
            if p.startswith(_LB_PREFIX)}
        controller_routes = {p: line for p, line in server_routes(
            idx, res, 'serve/controller.py').items()
            if p.startswith(_CONTROLLER_PREFIX)}

        # ---- threaded/async parity: routes, then header reads.
        threaded, asyncf = (front_routes.get(rel, {})
                            for rel in REPLICA_FRONTS)
        for path in sorted(set(threaded) - set(asyncf)):
            yield core.Finding(
                'http-front-parity', REPLICA_FRONTS[1], 0,
                f'route {path!r} is handled by the threaded front '
                f'only — the async front must expose the identical '
                f'surface')
        for path in sorted(set(asyncf) - set(threaded)):
            yield core.Finding(
                'http-front-parity', REPLICA_FRONTS[0], 0,
                f'route {path!r} is handled by the async front only '
                f'— the threaded front must expose the identical '
                f'surface')
        front_reads = {rel: header_reads(idx, res, rel)
                       for rel in REPLICA_FRONTS}
        t_reads, a_reads = (front_reads[rel] for rel in REPLICA_FRONTS)
        for header in sorted(set(t_reads) - set(a_reads)):
            yield core.Finding(
                'http-front-parity', REPLICA_FRONTS[1], 0,
                f'header {header!r} is read by the threaded front '
                f'only — async must honor it too')
        for header in sorted(set(a_reads) - set(t_reads)):
            yield core.Finding(
                'http-front-parity', REPLICA_FRONTS[0], 0,
                f'header {header!r} is read by the async front only '
                f'— threaded must honor it too')

        # ---- client paths hit registered routes (by namespace).
        replica_surface = set(threaded) | set(asyncf)
        for rel, line, path in sorted(set(client_paths(idx, res))):
            if path == '/':
                continue  # every GET answers the health payload
            if path.startswith(_LB_PREFIX):
                known = set(lb_routes)
                where = 'LB control plane'
            elif path.startswith(_CONTROLLER_PREFIX):
                known = set(controller_routes)
                where = 'controller'
            else:
                known = replica_surface
                where = 'replica fronts'
            if path not in known:
                yield core.Finding(
                    'http-unknown-route', rel, line,
                    f'client calls {path!r} but the {where} register '
                    f'no such route')

        # ---- headers: reads across all server modules vs stamps.
        all_reads: Dict[str, Tuple[str, int]] = {}
        for rel in SERVER_MODULES:
            for header, line in header_reads(idx, res, rel).items():
                all_reads.setdefault(header, (rel, line))
        stamps = header_stamps(idx, res)
        for header in sorted(all_reads):
            if not stamps.get(header):
                rel, line = all_reads[header]
                yield core.Finding(
                    'http-header-unstamped', rel, line,
                    f'server reads header {header!r} but nothing in '
                    f'the package stamps it on any request')
        for header in sorted(headers - set(all_reads)):
            yield core.Finding(
                'http-header-unread', PROTOCOL_MODULE, 0,
                f'canonical header {header!r} is read by no server '
                f'module — dead protocol surface, delete it or wire '
                f'the consumer')

        # ---- raw literals outside the protocol module.
        yield from self._raw_literals(idx, headers, paths)

        # ---- status codes.
        emittable = emitted_statuses(idx, res)
        for rel, line, code in sorted(set(
                client_status_branches(idx))):
            if 100 <= code < 600 and code not in emittable:
                yield core.Finding(
                    'http-status-unemittable', rel, line,
                    f'client branches on HTTP status {code}, which no '
                    f'server module can emit — dead branch or a '
                    f'contract typo')

        # ---- docs table.
        doc_dir = metrics_catalog.docs_root(idx)
        if doc_dir is not None and (doc_dir / _DOC).is_file():
            registered = (replica_surface | set(lb_routes) |
                          set(controller_routes))
            documented = documented_routes(doc_dir)
            for path in sorted(registered - documented):
                yield core.Finding(
                    'http-doc-drift', PROTOCOL_MODULE, 0,
                    f'route {path!r} is registered but missing '
                    f'from the docs/{_DOC} {_SECTION!r} table')
            for path in sorted(documented - registered):
                yield core.Finding(
                    'http-doc-drift', PROTOCOL_MODULE, 0,
                    f'docs/{_DOC} {_SECTION!r} table lists '
                    f'{path!r}, which no server registers')

    @staticmethod
    def _canonical(idx: index_lib.PackageIndex) -> Set[str]:
        """String constants defined at the protocol module's top level
        (headers + endpoint paths — the ban list for raw literals)."""
        mod = idx.modules[PROTOCOL_MODULE]
        values: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                values.add(node.value.value)
        return values

    def _raw_literals(self, idx: index_lib.PackageIndex,
                      headers: Set[str],
                      paths: Set[str]) -> Iterator[core.Finding]:
        banned = headers | paths
        quoted = [q for v in sorted(banned)
                  for q in (f"'{v}'", f'"{v}"')]
        for rel, mod in sorted(idx.modules.items()):
            if rel == PROTOCOL_MODULE:
                continue
            text = '\n'.join(mod.lines)
            if 'X-SkyTPU' not in text and \
                    not any(q in text for q in quoted):
                continue
            doc_ids = _docstring_ids(mod.tree)
            seen: Set[Tuple[int, str]] = set()
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Constant) or \
                        not isinstance(node.value, str):
                    continue
                if id(node) in doc_ids:
                    continue
                value = node.value
                if value in banned or _HEADER_RE.match(value):
                    key = (node.lineno, value)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield core.Finding(
                        'http-raw-literal', rel, node.lineno,
                        f'raw protocol literal {value!r} — import it '
                        f'from serve/http_protocol.py instead (the '
                        f'contract has one home)')
