"""`env-*`: every SKYTPU_* knob the code reads is documented, and
every documented knob still exists in code.

The knob registry is docs/environment-variables.md: a backticked
``SKYTPU_*`` name in the FIRST cell of a markdown table row documents
that knob.  Code side, any string literal that IS a ``SKYTPU_*`` name
counts as a reference — read sites (`os.environ.get`), export sites
(the skylet contract builds the env it ships to ranks), and default
maps all pin the name the same way, and a knob that exists only as an
export is still part of the user-facing contract.

Directionality is asymmetric on purpose:

- code -> docs runs over the package only: a knob the package
  references must be documented.
- docs -> code also accepts references under ``tests/`` and the
  top-level ``bench*.py`` drivers: a knob like the tier-1 wall-clock
  budget is consumed by the test harness, not the package, but its
  doc row is not stale.
"""
from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, Iterator, List, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib
from skypilot_tpu.analysis.passes import metrics_catalog

_DOC = 'environment-variables.md'
_NAME_RE = re.compile(r'^SKYTPU_[A-Z0-9_]+$')
_DOC_NAME_RE = re.compile(r'`(SKYTPU_[A-Z0-9_]+)`')


def package_references(idx: index_lib.PackageIndex) \
        -> Dict[str, List[Tuple[str, int]]]:
    """knob name -> [(file, line)] for every SKYTPU_* string literal
    in the package."""
    refs: Dict[str, List[Tuple[str, int]]] = {}
    for rel, mod in sorted(idx.modules.items()):
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Constant) and
                    isinstance(node.value, str) and
                    _NAME_RE.match(node.value)):
                refs.setdefault(node.value, []).append(
                    (rel, node.lineno))
    return refs


def harness_references(idx: index_lib.PackageIndex) -> Set[str]:
    """SKYTPU_* literals in tests/ and bench*.py (docs->code
    direction only; parse failures in a test file are its own
    test run's problem, not lint's)."""
    repo = idx.root.parent
    names: Set[str] = set()
    paths: List[pathlib.Path] = sorted(
        list((repo / 'tests').rglob('*.py')) +
        list(repo.glob('bench*.py')))
    for path in paths:
        if '__pycache__' in path.as_posix():
            continue
        try:
            tree = ast.parse(path.read_text(encoding='utf-8'))
        except (SyntaxError, OSError):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant) and
                    isinstance(node.value, str) and
                    _NAME_RE.match(node.value)):
                names.add(node.value)
    return names


def documented_knobs(doc_dir: pathlib.Path) -> Set[str]:
    doc = (doc_dir / _DOC).read_text(encoding='utf-8')
    names: Set[str] = set()
    for line in doc.splitlines():
        if not line.startswith('|'):
            continue
        cells = line.split('|')
        if len(cells) < 2:
            continue
        names.update(_DOC_NAME_RE.findall(cells[1]))
    return names


class EnvKnobsPass(core.Pass):

    name = 'env-knobs'
    rules = ('env-undocumented', 'env-stale-doc')
    description = ('SKYTPU_* knobs registered in '
                   'docs/environment-variables.md, both directions')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        doc_dir = metrics_catalog.docs_root(idx)
        if doc_dir is None or not (doc_dir / _DOC).is_file():
            return
        refs = package_references(idx)
        documented = documented_knobs(doc_dir)
        for name in sorted(set(refs) - documented):
            rel, line = refs[name][0]
            yield core.Finding(
                'env-undocumented', rel, line,
                f'env knob {name!r} is not documented in docs/{_DOC} '
                f'(add a table row)')
        known = set(refs) | harness_references(idx)
        for name in sorted(documented - known):
            yield core.Finding(
                'env-stale-doc', 'skylet/constants.py', 0,
                f'docs/{_DOC} documents {name!r} but nothing in the '
                f'package, tests/, or bench drivers references it '
                f'(delete the row or restore the knob)')
