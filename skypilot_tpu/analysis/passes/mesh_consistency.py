"""`mesh-*`: sharding axis names are real mesh axes, and donated jit
arguments are not reused.

Tracer-safety v2, for the failure modes that only surface on real TPU
meshes (CPU emulation shards trivially, so tier-1 tests can't see
them):

- **mesh-unknown-axis** — a string axis name in a
  `PartitionSpec(...)` (including through a `P = jax.sharding.
  PartitionSpec` alias, and therefore every `NamedSharding` /
  `with_sharding_constraint` / `device_put` built on one) must be an
  axis of a mesh some call site in the package constructs.  The known
  set is derived from the ASTs: `Mesh(devices, axis_names)` arguments
  (resolved through local/module constants like
  `DCN_AXES + ICI_AXES`), plus the literal keys of axis dicts
  returned by `*axes*` factory functions (`slice_axes`).  A typo'd
  axis passes every CPU test and fails only when GSPMD partitions on
  hardware.
- **mesh-donated-reuse** — an argument donated to a jitted function
  (`donate_argnums`) whose buffer is read again after the call: the
  donated buffer is invalid, and XLA's error (or silent alias) only
  reproduces on device.  Flagged when a plain-name argument at a
  donated position is loaded again after the call before being
  rebound (assignment targets bind AFTER the call's value computes,
  so `state = step(state)` is clean).

Both checks are conservative: non-literal axis names and non-Name
donated arguments resolve to "unknown" and are skipped, never
guessed.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib


# ------------------------------------------------------- axis collection


def _collect_strs(idx: index_lib.PackageIndex, rel: str,
                  expr: ast.AST, scope: Optional[ast.AST],
                  depth: int = 0) -> List[str]:
    """Literal strings reachable from a constant-ish expression:
    tuples, concatenation, list()/tuple() wrappers, local and module
    names, cross-module constants."""
    if depth > 8 or expr is None:
        return []
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in expr.elts:
            out.extend(_collect_strs(idx, rel, elt, scope, depth + 1))
        return out
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return (_collect_strs(idx, rel, expr.left, scope, depth + 1) +
                _collect_strs(idx, rel, expr.right, scope, depth + 1))
    if isinstance(expr, ast.Call) and \
            idx.callee_name(expr) in ('list', 'tuple') and expr.args:
        return _collect_strs(idx, rel, expr.args[0], scope, depth + 1)
    if isinstance(expr, ast.Name):
        out = []
        if scope is not None:
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == expr.id
                        for t in node.targets):
                    out.extend(_collect_strs(idx, rel, node.value,
                                             scope, depth + 1))
        if not out:
            mod = idx.modules.get(rel)
            if mod is not None:
                for node in mod.tree.body:
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and
                            t.id == expr.id for t in node.targets):
                        out.extend(_collect_strs(idx, rel, node.value,
                                                 None, depth + 1))
        return out
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name):
        trel = idx.resolve_module_alias(rel, expr.value.id)
        if trel is not None:
            mod = idx.modules.get(trel)
            if mod is not None:
                out = []
                for node in mod.tree.body:
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and
                            t.id == expr.attr for t in node.targets):
                        out.extend(_collect_strs(idx, trel, node.value,
                                                 None, depth + 1))
                return out
    return []


def known_axes(idx: index_lib.PackageIndex) -> Set[str]:
    """Every axis name some mesh constructor in the package can
    produce, plus the logical axis names a *_AXIS_RULES table maps to
    mesh axes (PartitionSpecs fed through logical_to_mesh_sharding
    legitimately carry those)."""
    axes: Set[str] = set()
    by_module: Dict[str, List[ast.AST]] = {}
    for (frel, qual), fn in sorted(idx.functions.items()):
        by_module.setdefault(frel, []).append(fn.node)
        # Axis-dict factories: literal keys of dicts returned by
        # functions whose name mentions 'axes' (slice_axes).
        if 'axes' in qual.lower():
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Return) and \
                        isinstance(node.value, ast.Dict):
                    for key in node.value.keys:
                        if isinstance(key, ast.Constant) and \
                                isinstance(key.value, str):
                            axes.add(key.value)
    for rel, mod in sorted(idx.modules.items()):
        text = '\n'.join(mod.lines)
        if 'Mesh(' not in text and 'AXIS_RULES' not in text:
            continue
        # Mesh(devices, axis_names) calls, resolved per enclosing
        # function (axis_names is typically a local).
        scopes: List[Tuple[Optional[ast.AST], ast.AST]] = \
            [(None, mod.tree)]
        scopes.extend((fn, fn) for fn in by_module.get(rel, []))
        for scope, tree in scopes:
            for call in idx.iter_calls(tree):
                if idx.callee_name(call) != 'Mesh':
                    continue
                names_arg: Optional[ast.AST] = None
                if len(call.args) >= 2:
                    names_arg = call.args[1]
                for kw in call.keywords:
                    if kw.arg == 'axis_names':
                        names_arg = kw.value
                if names_arg is not None:
                    axes.update(_collect_strs(idx, rel, names_arg,
                                              scope))
        # Logical-axis rules tables: ('stage', 'pipeline') pairs in a
        # module-level *_AXIS_RULES assignment register the logical
        # name (the rules translate it to a real mesh axis).
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            if not any(isinstance(t, ast.Name) and
                       'AXIS_RULES' in t.id for t in targets):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, (ast.Tuple, ast.List)) and \
                            elt.elts and \
                            isinstance(elt.elts[0], ast.Constant) and \
                            isinstance(elt.elts[0].value, str):
                        axes.add(elt.elts[0].value)
    return axes


def _spec_aliases(idx: index_lib.PackageIndex, rel: str) -> Set[str]:
    """Local names bound to PartitionSpec (`P = jax.sharding.
    PartitionSpec`)."""
    mod = idx.modules.get(rel)
    aliases: Set[str] = set()
    if mod is None:
        return aliases
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        try:
            text = ast.unparse(node.value)
        except Exception:  # pylint: disable=broad-except
            continue
        if text.endswith('PartitionSpec'):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    aliases.add(tgt.id)
    return aliases


# ---------------------------------------------------------- donated jits


def _donated_positions(call: ast.Call) -> List[int]:
    for kw in call.keywords:
        if kw.arg == 'donate_argnums':
            out = []
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, int):
                    out.append(node.value)
            return out
    return []


class MeshConsistencyPass(core.Pass):

    name = 'mesh-consistency'
    rules = ('mesh-unknown-axis', 'mesh-donated-reuse')
    description = ('PartitionSpec axis names exist on a constructed '
                   'mesh; donated jit arguments are not read after '
                   'the call')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        axes = known_axes(idx)
        yield from self._check_specs(idx, axes)
        yield from self._check_donation(idx)

    # -------------------------------------------------------- axis names

    def _check_specs(self, idx: index_lib.PackageIndex,
                     axes: Set[str]) -> Iterator[core.Finding]:
        if not axes:
            return
        for rel, mod in sorted(idx.modules.items()):
            if 'PartitionSpec' not in '\n'.join(mod.lines):
                continue
            aliases = _spec_aliases(idx, rel) | {'PartitionSpec'}
            for call in idx.iter_calls(mod.tree):
                callee = idx.callee_name(call)
                if callee not in aliases:
                    continue
                if callee != 'PartitionSpec' and not \
                        isinstance(call.func, ast.Name):
                    continue
                for arg in list(call.args) + \
                        [kw.value for kw in call.keywords]:
                    for elt in ([arg] if not isinstance(
                            arg, (ast.Tuple, ast.List))
                            else list(arg.elts)):
                        if isinstance(elt, ast.Constant) and \
                                isinstance(elt.value, str) and \
                                elt.value not in axes:
                            yield core.Finding(
                                'mesh-unknown-axis', rel, call.lineno,
                                f'PartitionSpec axis {elt.value!r} is '
                                f'not an axis of any mesh this '
                                f'package constructs '
                                f'({", ".join(sorted(axes))}) — '
                                f'GSPMD fails on real TPU meshes')

    # ---------------------------------------------------------- donation

    def _check_donation(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        for rel, mod in sorted(idx.modules.items()):
            if 'donate_argnums' not in '\n'.join(mod.lines):
                continue
            # Donated-jit bindings: `g = jit(f, donate_argnums=...)`
            # and `self.X = jit(f, donate_argnums=...)`.
            donated: Dict[str, List[int]] = {}
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                if idx.callee_name(node.value) != 'jit':
                    continue
                positions = _donated_positions(node.value)
                if not positions:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        donated[tgt.id] = positions
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == 'self':
                        donated[f'self.{tgt.attr}'] = positions
            if not donated:
                continue
            for (frel, qual), fn in sorted(idx.functions.items()):
                if frel != rel:
                    continue
                yield from self._check_function(rel, fn.node, donated)

    def _check_function(self, rel: str, fn: ast.AST,
                        donated: Dict[str, List[int]]) \
            -> Iterator[core.Finding]:
        # Donated calls in this function: (position, donated arg name).
        calls: List[Tuple[Tuple[int, int], str, ast.Call]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            key = None
            if isinstance(func, ast.Name):
                key = func.id
            elif isinstance(func, ast.Attribute) and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id == 'self':
                key = f'self.{func.attr}'
            if key is None or key not in donated:
                continue
            for pos in donated[key]:
                if pos < len(node.args) and \
                        isinstance(node.args[pos], ast.Name):
                    calls.append(((node.lineno, node.col_offset),
                                  node.args[pos].id, node))
        if not calls:
            return
        # Name events: loads at their own position, stores at the END
        # of their assignment statement (Python binds targets after the
        # RHS computes, so `state = step(state)` rebinds cleanly).
        events: Dict[str, List[Tuple[Tuple[int, int], str]]] = {}
        watched = {name for _, name, _ in calls}
        call_arg_ids: Set[int] = set()
        for _, _, call in calls:
            call_arg_ids.update(id(n) for n in ast.walk(call))
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    for name in ast.walk(tgt):
                        if isinstance(name, ast.Name) and \
                                name.id in watched:
                            end = (getattr(node, 'end_lineno',
                                           node.lineno), 10 ** 9)
                            events.setdefault(name.id, []).append(
                                (end, 'store'))
            elif isinstance(node, ast.For):
                for name in ast.walk(node.target):
                    if isinstance(name, ast.Name) and \
                            name.id in watched:
                        events.setdefault(name.id, []).append(
                            ((name.lineno, name.col_offset), 'store'))
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in watched and \
                    id(node) not in call_arg_ids:
                events.setdefault(node.id, []).append(
                    ((node.lineno, node.col_offset), 'load'))
        for pos, name, call in calls:
            after = sorted(e for e in events.get(name, [])
                           if e[0] > pos)
            if after and after[0][1] == 'load':
                yield core.Finding(
                    'mesh-donated-reuse', rel, after[0][0][0],
                    f'{name!r} is donated to the jitted call at line '
                    f'{pos[0]} and read again afterwards — the '
                    f'donated buffer is invalid on real devices; '
                    f'rebind the result or drop donate_argnums')
