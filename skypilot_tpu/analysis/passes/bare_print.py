"""`bare-print`: no bare print() outside the stdout-is-the-product set.

Migrated from the ad-hoc walker in tests/unit/test_no_bare_print.py
(ISSUE 4 satellite; the test is now a thin wrapper over this pass).
Diagnostics must go through sky_logging so they land in the log
infrastructure and the flight recorder, not a lost stdout.  AST-based,
not grep-based: codegen modules build ``print(...)`` INSIDE string
literals shipped to remote hosts and those are fine — only real
`print` call nodes count.
"""
from __future__ import annotations

import ast
from typing import Iterator

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib

# rel-path -> why stdout is the interface there.
ALLOWED = {
    'cli.py': 'click CLI: echo/table output is the product',
    'skylet/log_lib.py': 'log tailing: stdout is the data channel',
    'skylet/attempt_skylet.py': 'spawn status for the invoking shell',
    'native/__init__.py': 'fan-in line mirroring to the supervisor log',
    'models/import_weights.py': 'conversion script: JSON result on stdout',
    'jobs/core.py': 'tail_logs dumps the controller log to stdout',
    'serve/core.py': 'tail_logs dumps the service log to stdout',
    'chaos/elastic_task.py':
        'gang-exec\'d task: stdout is the rank log `sky logs` tails',
    'serve/slice_replica.py':
        '--bench-prefill prints its JSON result on stdout (bench_serve '
        'subprocess protocol)',
    'batch/runner.py':
        'managed-job driver: the summary JSON on stdout is the run '
        'output `sky jobs logs` tails',
}


class BarePrintPass(core.Pass):

    name = 'bare-print'
    rules = ('bare-print', 'bare-print-stale-allow')
    description = ('print() outside the allowlist (use sky_logging); '
                   'stale allowlist entries')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        for rel in sorted(ALLOWED):
            if rel not in idx.modules:
                yield core.Finding(
                    'bare-print-stale-allow', rel, 0,
                    f'allowlisted file {rel!r} no longer exists — '
                    f'shrink the allowlist in analysis/passes/'
                    f'bare_print.py')
        for rel, mod in sorted(idx.modules.items()):
            if rel in ALLOWED:
                continue
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Name) and
                        node.func.id == 'print'):
                    yield core.Finding(
                        'bare-print', rel, node.lineno,
                        'bare print() — use sky_logging.init_logger'
                        '(__name__), or allowlist the file with a '
                        'reason if stdout is its interface')
