"""The checker passes.  Rule catalog: docs/static-analysis.md."""
from typing import List

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis.passes.bare_print import BarePrintPass
from skypilot_tpu.analysis.passes.chaos_sites import ChaosSitesPass
from skypilot_tpu.analysis.passes.concurrency import ConcurrencyPass
from skypilot_tpu.analysis.passes.env_knobs import EnvKnobsPass
from skypilot_tpu.analysis.passes.facade_surface import (
    FacadeSurfacePass)
from skypilot_tpu.analysis.passes.http_contract import HttpContractPass
from skypilot_tpu.analysis.passes.journal_events import (
    JournalEventsPass)
from skypilot_tpu.analysis.passes.journal_protocol import (
    JournalProtocolPass)
from skypilot_tpu.analysis.passes.mesh_consistency import (
    MeshConsistencyPass)
from skypilot_tpu.analysis.passes.metrics_catalog import (
    MetricsCatalogPass)
from skypilot_tpu.analysis.passes.tracer_safety import TracerSafetyPass


def all_passes() -> List[core.Pass]:
    """Deterministic order (output sorting does not depend on it, but
    `--json`'s pass list does)."""
    return [
        ConcurrencyPass(),
        TracerSafetyPass(),
        MeshConsistencyPass(),
        EnvKnobsPass(),
        JournalEventsPass(),
        JournalProtocolPass(),
        HttpContractPass(),
        MetricsCatalogPass(),
        ChaosSitesPass(),
        BarePrintPass(),
        FacadeSurfacePass(),
    ]
