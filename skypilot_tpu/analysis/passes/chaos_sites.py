"""`chaos-site-*`: inject() call sites stay in lockstep with the
fault registry.

Migrated from tests/unit/test_chaos_sites_lint.py (ISSUE 5 satellite;
the test is now a thin wrapper).  Every ``inject(...)`` call site must
pass a *string literal* site name registered in ``chaos/faults.py``
(a computed site would dodge both this lint and the docs table), every
registered site must have at least one call site, and each site's
call sites must live in the layer its prefix documents — the
docs/chaos.md vocabulary table stays honest.

The registry is read from the AST of chaos/faults.py (``SITES``
mapping keys), not by importing it — the lint plane never imports
analyzed code.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib

_FAULTS_MODULE = 'chaos/faults.py'

# site prefix -> layer its call sites must live in (mirrors the
# docs/chaos.md vocabulary table).
EXPECTED_LAYER = {
    'provision.create': ('backends/', 'provision/'),
    'queued_resource.poll': ('provision/',),
    'runner.exec': ('utils/',),
    'gang.rank_exec': ('backends/',),
    'jobs.status_poll': ('jobs/',),
    'jobs.recover': ('jobs/',),
    'serve.replica_probe': ('serve/',),
    'serve.controller_tick': ('serve/',),
    'serve.page_pool': ('serve/',),
    'serve.kv_handoff': ('serve/',),
    'serve.rank_exec': ('serve/',),
    'serve.router_push': ('serve/',),
    'serve.role_morph': ('serve/',),
    'skylet.tick': ('skylet/',),
    'checkpoint.save': ('data/',),
    'batch.shard_write': ('batch/',),
}


def registered_sites(idx: index_lib.PackageIndex) -> List[str]:
    """SITES keys from the chaos/faults.py AST (string dict keys of a
    top-level ``SITES = {...}`` assignment, or ``SITES = (...)``)."""
    mod = idx.modules.get(_FAULTS_MODULE)
    if mod is None:
        return []
    for node in mod.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == 'SITES':
                value = getattr(node, 'value', None)
                if isinstance(value, ast.Dict):
                    return [k.value for k in value.keys
                            if isinstance(k, ast.Constant) and
                            isinstance(k.value, str)]
                if isinstance(value, (ast.List, ast.Tuple, ast.Set)):
                    return [e.value for e in value.elts
                            if isinstance(e, ast.Constant) and
                            isinstance(e.value, str)]
    return []


def inject_call_sites(idx: index_lib.PackageIndex) \
        -> Tuple[Dict[str, List[Tuple[str, int]]],
                 List[Tuple[str, int]]]:
    """(site -> [(file, line)]), plus non-literal inject() sites."""
    sites: Dict[str, List[Tuple[str, int]]] = {}
    non_literal: List[Tuple[str, int]] = []
    for rel, mod in sorted(idx.modules.items()):
        if rel.startswith('chaos/'):
            continue  # the subsystem itself, not an instrumented site
        for call in idx.iter_calls(mod.tree):
            if idx.callee_name(call) != 'inject':
                continue
            if (not call.args or
                    not isinstance(call.args[0], ast.Constant) or
                    not isinstance(call.args[0].value, str)):
                non_literal.append((rel, call.lineno))
                continue
            sites.setdefault(call.args[0].value, []).append(
                (rel, call.lineno))
    return sites, non_literal


class ChaosSitesPass(core.Pass):

    name = 'chaos-sites'
    rules = ('chaos-site-unregistered', 'chaos-site-computed',
             'chaos-site-stale', 'chaos-site-misplaced',
             'chaos-site-unmapped')
    description = ('inject() sites registered in chaos/faults.py, '
                   'registered sites instrumented, each in its '
                   'documented layer')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        if _FAULTS_MODULE not in idx.modules:
            return  # not this package (fixture trees in tests)
        registered = registered_sites(idx)
        call_sites, non_literal = inject_call_sites(idx)
        for rel, line in non_literal:
            yield core.Finding(
                'chaos-site-computed', rel, line,
                'inject() must take a string-literal site name as its '
                'first argument')
        for site in sorted(call_sites):
            if site not in registered:
                for rel, line in call_sites[site]:
                    yield core.Finding(
                        'chaos-site-unregistered', rel, line,
                        f'site {site!r} is not registered in '
                        f'chaos/faults.py SITES')
        for site in sorted(set(registered) - set(call_sites)):
            yield core.Finding(
                'chaos-site-stale', _FAULTS_MODULE, 0,
                f'site {site!r} registered in chaos/faults.py has no '
                f'inject() call site (remove it or instrument it)')
        # Layer map drift: the vocabulary changed but EXPECTED_LAYER
        # (and docs/chaos.md) did not.
        for site in sorted(set(registered) ^ set(EXPECTED_LAYER)):
            yield core.Finding(
                'chaos-site-unmapped', _FAULTS_MODULE, 0,
                f'site {site!r}: chaos/faults.py SITES and the '
                f'EXPECTED_LAYER map in analysis/passes/chaos_sites.py '
                f'disagree — update the map and docs/chaos.md')
        for site, prefixes in sorted(EXPECTED_LAYER.items()):
            for rel, line in call_sites.get(site, []):
                if not rel.startswith(prefixes):
                    yield core.Finding(
                        'chaos-site-misplaced', rel, line,
                        f'site {site!r} must be instrumented under '
                        f'{"/".join(prefixes)} (docs/chaos.md layer '
                        f'table)')
