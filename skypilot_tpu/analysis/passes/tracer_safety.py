"""`tracer-safety`: code reachable from a jit/shard_map/lax.scan
entry point must stay traceable.

On real TPUs a Python-side branch on a traced value either retraces
per step (silent 100x slowdown) or crashes with a
ConcretizationTypeError the CPU tests never see ("Exploring the limits
of Concurrency in ML Training on Google TPUs", PAPERS.md).  This pass
finds the traced region statically and flags host-semantics inside it:

- **Entry points**: first arguments of ``jax.jit`` / ``shard_map`` /
  ``sp_shard_map`` / ``jax.lax.scan`` / ``pl.pallas_call`` calls and
  ``@jit``-style decorators — including lambdas and
  ``functools.partial`` wrappers (partial-bound and
  ``static_argnums``/``static_argnames`` params are static; the rest
  are traced).  A ``pallas_call`` additionally registers every lambda
  in its spec arguments (BlockSpec index maps, grid maps): index maps
  run on traced grid indices, so host semantics there break or retrace
  exactly like a jit body.
- **Reachability**: calls from traced functions to package functions
  (same module, or through a module alias) extend the region.
- **Findings inside the region**:
  - Python branching (`if`/`while`/`for`) on a *tainted* expression —
    a traced param or a value derived from ``jnp.*``/``lax.*`` calls.
    ``x.shape``/``.ndim``/``.dtype`` access and ``is None`` checks
    stay static and are exempt.
  - ``int()``/``bool()``/``float()`` on tainted values and any
    ``.item()`` call — host concretization.
  - ``np.asarray``/``np.array`` on tainted values — device->host
    transfer inside the trace.
  - wall-clock reads (``time.time``/``perf_counter``/...) — traced
    once, frozen forever.
  - fresh constant-seed ``PRNGKey``/``random.key`` — the "random"
    stream is identical every call.

Taint tracking is intentionally local (per function, no loop
fixpoint): callee parameters without array annotations are NOT
assumed traced, so static-config branching in model code stays clean.
False negatives are possible; false positives should be rare — and
suppressable with a reason.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib

_JIT_NAMES = {'jit'}
_SHARD_MAP_NAMES = {'shard_map', 'sp_shard_map', '_shard_map'}
_SCAN_NAMES = {'scan'}
# Pallas kernel launches: the kernel body traces like a jit entry
# (Refs in, Refs out), and index-map/grid lambdas trace on grid
# indices.
_PALLAS_NAMES = {'pallas_call'}
_WALL_CLOCK = {'time', 'perf_counter', 'monotonic', 'time_ns', 'now'}
_KEY_FACTORIES = {'PRNGKey', 'key'}
_STATIC_ATTRS = {'shape', 'ndim', 'dtype', 'size', 'sharding',
                 'weak_type'}
_ARRAY_ANNOTATIONS = ('Array', 'ndarray')
# Call bases producing traced values (resolved through import aliases).
_TRACED_BASES = {'jax', 'jnp', 'lax'}
_TRACED_BASE_MODULES = {'jax', 'jax.numpy', 'jax.lax'}


@dataclasses.dataclass
class _Unit:
    """One function body in the traced region."""
    rel: str
    label: str
    node: ast.AST                   # FunctionDef / Lambda
    traced_params: Set[str]
    is_entry: bool


def _param_names(node: ast.AST) -> List[str]:
    """Positional params only: keyword-only params are config in this
    codebase (mesh, axis names, bucket widths) and never trace."""
    args = node.args
    return [a.arg for a in args.posonlyargs + args.args]


def _static_from_jit_call(call: ast.Call, params: List[str]) \
        -> Set[str]:
    """Params pinned static by static_argnums / static_argnames."""
    static: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == 'static_argnums':
            for idx_const in ast.walk(kw.value):
                if (isinstance(idx_const, ast.Constant) and
                        isinstance(idx_const.value, int) and
                        0 <= idx_const.value < len(params)):
                    static.add(params[idx_const.value])
        elif kw.arg == 'static_argnames':
            for name_const in ast.walk(kw.value):
                if (isinstance(name_const, ast.Constant) and
                        isinstance(name_const.value, str)):
                    static.add(name_const.value)
    return static


class _TaintChecker:
    """Expression-level taint: does this expression depend on a traced
    value at trace time?"""

    def __init__(self, mod: index_lib.ModuleInfo,
                 tainted: Set[str]) -> None:
        self.mod = mod
        self.tainted = tainted

    def _traced_factory(self, call: ast.Call) -> bool:
        """jnp.zeros(...) / lax.scan(...) / jax.numpy... produce
        traced values inside a traced region."""
        node = call.func
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return False
        dotted = self.mod.import_aliases.get(node.id)
        if dotted is None:
            return node.id in _TRACED_BASES
        return (dotted in _TRACED_BASE_MODULES or
                dotted.split('.')[0] == 'jax')

    def tainted_expr(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self.tainted_expr(expr.value)
        if isinstance(expr, ast.Subscript):
            return self.tainted_expr(expr.value)
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Name) and func.id == 'len':
                return False
            if (isinstance(func, ast.Attribute) and
                    func.attr == 'psum' and expr.args and
                    isinstance(expr.args[0], ast.Constant)):
                # psum of a literal is the axis-size idiom — concrete
                # (static) under shard_map, not a traced value.
                return False
            if isinstance(func, ast.Attribute):
                if self.tainted_expr(func.value):
                    return True
            if self._traced_factory(expr):
                return True
            return any(self.tainted_expr(a) for a in expr.args) or \
                any(self.tainted_expr(kw.value)
                    for kw in expr.keywords)
        if isinstance(expr, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in expr.ops):
                return False
            return (self.tainted_expr(expr.left) or
                    any(self.tainted_expr(c)
                        for c in expr.comparators))
        if isinstance(expr, ast.BoolOp):
            return any(self.tainted_expr(v) for v in expr.values)
        if isinstance(expr, (ast.BinOp,)):
            return (self.tainted_expr(expr.left) or
                    self.tainted_expr(expr.right))
        if isinstance(expr, ast.UnaryOp):
            return self.tainted_expr(expr.operand)
        if isinstance(expr, ast.IfExp):
            return (self.tainted_expr(expr.test) or
                    self.tainted_expr(expr.body) or
                    self.tainted_expr(expr.orelse))
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.tainted_expr(e) for e in expr.elts)
        return False


def _array_annotated(node: ast.AST) -> Set[str]:
    """Params whose annotation names an array type."""
    out: Set[str] = set()
    args = node.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        if a.annotation is None:
            continue
        try:
            text = ast.unparse(a.annotation)
        except Exception:  # pylint: disable=broad-except
            continue
        if any(marker in text for marker in _ARRAY_ANNOTATIONS):
            out.add(a.arg)
    return out


def _nested_defs(fn: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _find_entries(idx: index_lib.PackageIndex) -> List[_Unit]:
    """Every function object handed to jit / shard_map / lax.scan."""
    units: List[_Unit] = []
    seen: Set[int] = set()

    def add(rel: str, label: str, node: ast.AST,
            traced: Set[str]) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        units.append(_Unit(rel, label, node, traced, True))

    def resolve_target(rel: str, expr: ast.AST,
                       scope: Dict[str, ast.AST]) \
            -> Optional[Tuple[str, str, ast.AST]]:
        if isinstance(expr, ast.Lambda):
            return (rel, '<lambda>', expr)
        if isinstance(expr, ast.Name):
            if expr.id in scope:
                return (rel, expr.id, scope[expr.id])
            key = (rel, expr.id)
            if key in idx.functions:
                return (rel, expr.id, idx.functions[key].node)
            return None
        if (isinstance(expr, ast.Attribute) and
                isinstance(expr.value, ast.Name)):
            target = idx.resolve_module_alias(rel, expr.value.id)
            if target is not None and \
                    (target, expr.attr) in idx.functions:
                return (target, expr.attr,
                        idx.functions[(target, expr.attr)].node)
        return None

    def register(rel: str, call: ast.Call, kind: str,
                 scope: Dict[str, ast.AST],
                 partial_bindings: Dict[str, List[ast.Call]]) -> None:
        if not call.args:
            return
        # Unwrap functools.partial(fn, a, b, kw=...) — inline, or
        # name-bound a few lines up (`kernel = partial(fn, ...)`, the
        # pallas_call idiom where specs and kernel build together).
        candidates: List[Tuple[ast.AST, int, Set[str]]] = []
        target = call.args[0]
        if (isinstance(target, ast.Call) and
                idx.callee_name(target) == 'partial' and
                target.args):
            candidates.append(
                (target.args[0], len(target.args) - 1,
                 {kw.arg for kw in target.keywords if kw.arg}))
        elif (isinstance(target, ast.Name) and
              target.id in partial_bindings):
            for bound in partial_bindings[target.id]:
                candidates.append(
                    (bound.args[0], len(bound.args) - 1,
                     {kw.arg for kw in bound.keywords if kw.arg}))
        else:
            candidates.append((target, 0, set()))
        for target, bound_pos, bound_kw in candidates:
            got = resolve_target(rel, target, scope)
            if got is None:
                continue
            trel, label, node = got
            # Keyword-only params are config in this codebase (mesh,
            # axis names, bucket widths) — bound in the partial or
            # left at their default, never traced.  Only positional
            # params trace.
            params = _param_names(node)
            static = set(params[:bound_pos]) | bound_kw
            if kind == 'jit':
                static |= _static_from_jit_call(call, params)
            traced = {p for p in params
                      if p not in static and p not in ('self', 'cls')}
            add(trel, label, node, traced)

    for rel, mod in sorted(idx.modules.items()):
        # Whole-module walk: jit() calls appear at module level
        # (`step_jit = jax.jit(step)`), in __init__ bodies, anywhere.
        scope = _nested_defs(mod.tree)
        partial_bindings: Dict[str, List[ast.Call]] = {}
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Assign) and
                    isinstance(node.value, ast.Call) and
                    idx.callee_name(node.value) == 'partial' and
                    node.value.args):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        partial_bindings.setdefault(
                            tgt.id, []).append(node.value)
        for call in idx.iter_calls(mod.tree):
            callee = idx.callee_name(call)
            if callee in _JIT_NAMES:
                register(rel, call, 'jit', scope, partial_bindings)
            elif callee in _SHARD_MAP_NAMES:
                register(rel, call, 'shard_map', scope,
                         partial_bindings)
            elif callee in _SCAN_NAMES:
                register(rel, call, 'scan', scope, partial_bindings)
            elif callee in _PALLAS_NAMES:
                # The kernel body is a traced entry (every positional
                # param is a Ref the grid loop hands in)...
                register(rel, call, 'pallas', scope, partial_bindings)
                # ...and so is every lambda in the spec arguments:
                # BlockSpec index maps and grid maps run on traced
                # grid indices.
                for holder in (list(call.args[1:]) +
                               [kw.value for kw in call.keywords]):
                    for sub in ast.walk(holder):
                        if isinstance(sub, ast.Lambda):
                            add(rel, '<pallas index_map>', sub,
                                set(_param_names(sub)))
        # Decorators: @jax.jit / @functools.partial(jax.jit, ...).
        for fn_key, fn in sorted(idx.functions.items()):
            if fn_key[0] != rel:
                continue
            node = fn.node
            for dec in getattr(node, 'decorator_list', []):
                dec_call = dec if isinstance(dec, ast.Call) else None
                name = None
                if isinstance(dec, ast.Name):
                    name = dec.id
                elif isinstance(dec, ast.Attribute):
                    name = dec.attr
                elif dec_call is not None:
                    name = idx.callee_name(dec_call)
                    if name == 'partial' and dec_call.args:
                        inner = dec_call.args[0]
                        name = (inner.attr if isinstance(
                            inner, ast.Attribute) else
                            inner.id if isinstance(inner, ast.Name)
                            else None)
                if name in _JIT_NAMES:
                    params = _param_names(node)
                    static: Set[str] = set()
                    if dec_call is not None:
                        static = _static_from_jit_call(dec_call,
                                                       params)
                    add(rel, fn_key[1], node,
                        {p for p in params if p not in static})
    return units


def _reachable(idx: index_lib.PackageIndex,
               entries: List[_Unit]) -> List[_Unit]:
    """Close the region over intra-package calls."""
    units = list(entries)
    seen_fns: Set[Tuple[str, str]] = set()
    for u in units:
        for key, fn in idx.functions.items():
            if fn.node is u.node:
                seen_fns.add(key)
    queue = list(units)
    while queue:
        u = queue.pop()
        for call in idx.iter_calls(u.node):
            func = call.func
            key: Optional[Tuple[str, str]] = None
            if isinstance(func, ast.Name):
                key = (u.rel, func.id)
            elif (isinstance(func, ast.Attribute) and
                  isinstance(func.value, ast.Name)):
                target = idx.resolve_module_alias(u.rel,
                                                  func.value.id)
                if target is not None:
                    key = (target, func.attr)
            if key is None or key in seen_fns or \
                    key not in idx.functions:
                continue
            seen_fns.add(key)
            node = idx.functions[key].node
            callee_unit = _Unit(key[0], key[1], node,
                                _array_annotated(node), False)
            units.append(callee_unit)
            queue.append(callee_unit)
    return units


class TracerSafetyPass(core.Pass):

    name = 'tracer-safety'
    rules = ('tracer-safety',)
    description = ('no host-side branching/concretization/wall-clock/'
                   'fresh PRNG keys inside jit/shard_map/scan traced '
                   'regions')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        units = _reachable(idx, _find_entries(idx))
        emitted: Set[Tuple[str, int, str]] = set()
        for u in sorted(units, key=lambda u: (u.rel, u.label)):
            mod = idx.modules[u.rel]
            for f in self._check_unit(idx, mod, u):
                dedup = (f.file, f.line, f.message)
                if dedup not in emitted:
                    emitted.add(dedup)
                    yield f

    def _check_unit(self, idx: index_lib.PackageIndex,
                    mod: index_lib.ModuleInfo,
                    u: _Unit) -> Iterator[core.Finding]:
        tainted = set(u.traced_params)
        checker = _TaintChecker(mod, tainted)
        where = f'traced region via {u.label}'

        body = (u.node.body if isinstance(u.node.body, list)
                else [u.node.body])
        for stmt in body:
            for node in ast.walk(stmt):
                # Taint propagation through simple assignments, in
                # source order (ast.walk is close enough for lint).
                if isinstance(node, ast.Assign):
                    if checker.tainted_expr(node.value):
                        for tgt in node.targets:
                            for name in ast.walk(tgt):
                                if isinstance(name, ast.Name):
                                    tainted.add(name.id)
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.If, ast.While)):
                    if checker.tainted_expr(node.test):
                        yield core.Finding(
                            'tracer-safety', u.rel, node.lineno,
                            f'Python branch on a traced value '
                            f'({where}) — use lax.cond/lax.select or '
                            f'hoist the value out of the trace')
                elif isinstance(node, ast.For):
                    if checker.tainted_expr(node.iter):
                        yield core.Finding(
                            'tracer-safety', u.rel, node.lineno,
                            f'Python iteration over a traced value '
                            f'({where}) — use lax.scan/fori_loop')
                elif isinstance(node, ast.Call):
                    yield from self._check_call(idx, mod, checker,
                                                u, node, where)

    def _check_call(self, idx: index_lib.PackageIndex,
                    mod: index_lib.ModuleInfo,
                    checker: _TaintChecker, u: _Unit,
                    call: ast.Call, where: str) \
            -> Iterator[core.Finding]:
        callee = idx.callee_name(call)
        func = call.func
        if callee == 'item' and isinstance(func, ast.Attribute):
            yield core.Finding(
                'tracer-safety', u.rel, call.lineno,
                f'.item() concretizes on host ({where}) — a traced '
                f'operand crashes the trace')
            return
        if (callee in ('int', 'bool', 'float') and
                isinstance(func, ast.Name) and call.args and
                checker.tainted_expr(call.args[0])):
            yield core.Finding(
                'tracer-safety', u.rel, call.lineno,
                f'{callee}() on a traced value ({where}) — '
                f'ConcretizationTypeError on real inputs')
            return
        if callee in ('asarray', 'array') and \
                isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            dotted = mod.import_aliases.get(func.value.id, '')
            if dotted.split('.')[0] == 'numpy' and call.args and \
                    checker.tainted_expr(call.args[0]):
                yield core.Finding(
                    'tracer-safety', u.rel, call.lineno,
                    f'np.{callee}() on a traced value ({where}) — '
                    f'forces a device->host transfer inside the '
                    f'trace')
                return
        if callee in _WALL_CLOCK and isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            dotted = mod.import_aliases.get(func.value.id,
                                            func.value.id)
            if dotted.split('.')[0] in ('time', 'datetime'):
                yield core.Finding(
                    'tracer-safety', u.rel, call.lineno,
                    f'wall-clock read inside a traced region '
                    f'({where}) — traced once, frozen into the '
                    f'compiled graph')
                return
        if callee in _KEY_FACTORIES and \
                isinstance(func, ast.Attribute) and call.args and \
                isinstance(call.args[0], ast.Constant):
            base = func.value
            text = ast.unparse(base) if base is not None else ''
            if 'random' in text:
                yield core.Finding(
                    'tracer-safety', u.rel, call.lineno,
                    f'fresh constant-seed PRNGKey inside a traced '
                    f'region ({where}) — the stream repeats every '
                    f'call; thread keys in as arguments')
