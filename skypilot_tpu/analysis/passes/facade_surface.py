"""`facade-*`: serve/batching_engine.py re-exports exactly the public
surface of the engine's three parts.

PR 7 split the continuous-batching engine into scheduler.py /
cache_manager.py / sampler.py and left batching_engine.py as the
compatibility facade.  A facade drifts silently: a class added to
scheduler.py is invisible to facade importers until someone notices,
and a renamed one leaves a stale re-export that fails only at import
time of the one module that still uses it.  This pass pins both
directions, from the ASTs alone:

- `facade-missing`: a public top-level name of a part module with no
  same-name ``X = <part>.X`` re-export in the facade.
- `facade-stale`: a facade re-export ``Y = <part>.X`` (any Y,
  including the underscore compat aliases) naming an X that no longer
  exists at the part's top level.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib

FACADE = 'serve/batching_engine.py'
PARTS = ('serve/scheduler.py', 'serve/cache_manager.py',
         'serve/sampler.py')
# Module plumbing every part defines for itself — not facade surface.
_NOT_SURFACE = {'logger'}


def public_surface(idx: index_lib.PackageIndex, rel: str) -> Set[str]:
    """Public top-level defs of one module (classes, functions,
    constants; imports and underscore names excluded)."""
    mod = idx.modules[rel]
    names: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            names.update(t.id for t in node.targets
                         if isinstance(t, ast.Name))
        elif (isinstance(node, ast.AnnAssign) and
              isinstance(node.target, ast.Name)):
            names.add(node.target.id)
    return {n for n in names
            if not n.startswith('_') and n not in _NOT_SURFACE}


def facade_reexports(idx: index_lib.PackageIndex) \
        -> List[Tuple[str, str, str, int]]:
    """[(local_name, part_rel, part_attr, line)] for every top-level
    ``name = <part_alias>.attr`` in the facade."""
    mod = idx.modules[FACADE]
    out: List[Tuple[str, str, str, int]] = []
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not (isinstance(value, ast.Attribute) and
                isinstance(value.value, ast.Name)):
            continue
        part = idx.resolve_module_alias(FACADE, value.value.id)
        if part not in PARTS:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.append((tgt.id, part, value.attr, node.lineno))
    return out


class FacadeSurfacePass(core.Pass):

    name = 'facade-surface'
    rules = ('facade-missing', 'facade-stale')
    description = ('batching_engine facade re-exports the full public '
                   'surface of scheduler + cache_manager + sampler, '
                   'nothing stale')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        if FACADE not in idx.modules:
            return
        reexports = facade_reexports(idx)
        same_name: Dict[str, Set[str]] = {}
        for local, part, attr, _ in reexports:
            if local == attr:
                same_name.setdefault(part, set()).add(local)
        for part in PARTS:
            if part not in idx.modules:
                continue
            surface = public_surface(idx, part)
            for name in sorted(surface -
                               same_name.get(part, set())):
                yield core.Finding(
                    'facade-missing', FACADE, 0,
                    f'public name {part}:{name} is not re-exported '
                    f'by the facade (add `{name} = '
                    f'{part.rsplit("/", 1)[-1][:-3]}.{name}`)')
        for local, part, attr, line in sorted(
                set(reexports), key=lambda r: (r[3], r[0])):
            if (part in idx.modules and
                    attr not in _all_top_level(idx, part)):
                yield core.Finding(
                    'facade-stale', FACADE, line,
                    f'facade re-export {local} = ...{attr} names an '
                    f'attribute {part} no longer defines')


def _all_top_level(idx: index_lib.PackageIndex, rel: str) -> Set[str]:
    """Every top-level binding (incl. underscore names): staleness is
    about existence, not publicness."""
    mod = idx.modules[rel]
    names: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            names.update(t.id for t in node.targets
                         if isinstance(t, ast.Name))
        elif (isinstance(node, ast.AnnAssign) and
              isinstance(node.target, ast.Name)):
            names.add(node.target.id)
    return names
