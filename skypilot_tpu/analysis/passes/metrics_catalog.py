"""`metrics-*`: the docs/observability.md catalog stays in lockstep
with the instruments the code registers.

Migrated from tests/unit/test_metrics_catalog_lint.py (ISSUE 11
satellite; the test is now a thin wrapper).  Every ``skytpu_*``
instrument registered anywhere in the package (a string-literal first
argument to a ``counter``/``gauge``/``histogram`` constructor) must
appear in the catalog tables (a backticked name in the first cell of
a markdown table row), and every catalog row must name a series that
still exists in code — no undocumented telemetry, no stale catalog
entries, in either direction.
"""
from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib

_CONSTRUCTORS = ('counter', 'gauge', 'histogram')
_DOC = 'observability.md'


def docs_root(idx: index_lib.PackageIndex) -> Optional[pathlib.Path]:
    """The repo's docs/ directory (package root's sibling); None when
    linting an installed tree with no docs checkout."""
    cand = idx.root.parent / 'docs'
    return cand if cand.is_dir() else None


def registered_series(idx: index_lib.PackageIndex) \
        -> Dict[str, List[Tuple[str, int]]]:
    names: Dict[str, List[Tuple[str, int]]] = {}
    for rel, mod in sorted(idx.modules.items()):
        for call in idx.iter_calls(mod.tree):
            if idx.callee_name(call) not in _CONSTRUCTORS:
                continue
            if not call.args:
                continue
            first = call.args[0]
            if not (isinstance(first, ast.Constant) and
                    isinstance(first.value, str)):
                continue
            if not first.value.startswith('skytpu_'):
                continue
            names.setdefault(first.value, []).append(
                (rel, call.lineno))
    return names


def documented_series(doc_dir: pathlib.Path) -> Set[str]:
    """Series named in the catalog tables (a backticked `skytpu_*`
    in the first cell of a markdown table row)."""
    doc = (doc_dir / _DOC).read_text(encoding='utf-8')
    names: Set[str] = set()
    for line in doc.splitlines():
        if not line.startswith('|'):
            continue
        cells = line.split('|')
        if len(cells) < 2:
            continue
        names.update(re.findall(r'`(skytpu_[a-z0-9_]+)`', cells[1]))
    return names


class MetricsCatalogPass(core.Pass):

    name = 'metrics-catalog'
    rules = ('metrics-undocumented', 'metrics-stale-doc')
    description = ('skytpu_* instruments cataloged in '
                   'docs/observability.md, both directions')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        doc_dir = docs_root(idx)
        if doc_dir is None or not (doc_dir / _DOC).is_file():
            return
        registered = registered_series(idx)
        documented = documented_series(doc_dir)
        for name in sorted(set(registered) - documented):
            rel, line = registered[name][0]
            yield core.Finding(
                'metrics-undocumented', rel, line,
                f'instrument {name!r} is not in the '
                f'docs/{_DOC} catalog tables (add a row)')
        for name in sorted(documented - set(registered)):
            yield core.Finding(
                'metrics-stale-doc', 'observability/metrics.py', 0,
                f'docs/{_DOC} catalogs series {name!r} that no code '
                f'registers (delete the row or restore the '
                f'instrument)')
