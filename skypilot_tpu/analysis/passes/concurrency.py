"""`lock-order` / `blocking-under-lock` / `unlocked-attr`: the
concurrency race detector.

The serve fleet is a heavily threaded system (router, load balancer,
engine, page pool, coordinator) whose correctness rests on lock
discipline no test can exhaustively exercise.  This pass derives the
lock structure statically:

- **Lock registry.**  Every ``threading.Lock/RLock/Condition/
  Semaphore`` bound to a class attribute (``self._lock = ...``), a
  module global, or a function local is a lock identity.
- **Lock graph / `lock-order`.**  A walker tracks the held-lock stack
  through each function, one level of attribute-type inference
  (``self.router = Router()``) plus a transitive-closure fixpoint over
  the intra-package call graph resolves which locks a call acquires,
  and every (held -> acquired) pair is an edge.  Cycles in the global
  edge set — including a plain (non-reentrant) Lock re-acquired while
  held through any call chain — are ordered-deadlock findings.
- **`blocking-under-lock`.**  While any lock is held, calls that can
  block indefinitely or do I/O are flagged: HTTP/sockets
  (``requests.*``, ``urllib``, ``socket.create_connection``),
  ``time.sleep``, subprocess spawns, file writes (``open``), journal
  appends, and JAX device transfers (``jax.device_put/device_get``,
  ``.block_until_ready()``).  ``Condition.wait`` is exempt — it
  releases the lock by contract.  Blocking-ness propagates through
  the call graph, so holding a lock across a helper that journals is
  flagged at the call site.
- **`unlocked-attr`.**  In a class that owns locks, an attribute
  written both under a lock and lock-free (outside ``__init__``) has
  no consistent guard — the classic lost-update smell.

Findings name the locks and the witness line; intended exceptions are
suppressed inline with a written reason (no blanket baselines for
`serve/` — see docs/static-analysis.md).
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib
from skypilot_tpu.analysis.passes.journal_events import _is_journalish

_REENTRANT_FACTORIES = ('RLock', 'Condition')
_LOCK_RELEASING_WAITS = ('wait', 'wait_for')

# (module alias base, callee) shapes that can block indefinitely or
# hit I/O.  `None` base = bare-call / any-receiver match.
_BLOCKING_MODULE_CALLS = {
    'time': {'sleep'},
    'requests': None,          # every requests.* call is network I/O
    'urllib': None,
    'socket': {'create_connection', 'getaddrinfo', 'gethostbyname'},
    'subprocess': {'run', 'Popen', 'call', 'check_call',
                   'check_output'},
    'os': {'system'},
    'jax': {'device_put', 'device_get'},
    'shutil': {'copy', 'copy2', 'copytree', 'move', 'rmtree'},
}
_BLOCKING_ATTR_CALLS = {'block_until_ready'}
_BLOCKING_BARE_CALLS = {'open'}


@dataclasses.dataclass(frozen=True)
class Lock:
    lock_id: str          # 'serve/router.py::Router._lock'
    reentrant: bool


@dataclasses.dataclass
class _FnFacts:
    """Per-function facts feeding the interprocedural fixpoint."""
    key: Tuple[str, str]
    acquires: Set[str]                     # locks taken anywhere in fn
    blocking: List[Tuple[int, str]]        # (line, what) direct blocks
    callees: Set[Tuple[str, str]]          # resolved package callees
    # (held lock, acquired lock, line) edges from direct nesting.
    edges: List[Tuple[str, str, int]]
    # (held locks, line, callee key) — call made while locks held.
    held_calls: List[Tuple[Tuple[str, ...], int, Tuple[str, str]]]
    # (held locks, line, what) — direct blocking while locks held.
    held_blocking: List[Tuple[Tuple[str, ...], int, str]]
    # attr writes: attr -> [(line, locked?)]   (methods only)
    attr_writes: Dict[str, List[Tuple[int, bool]]]


def _call_base_name(call: ast.Call) -> Optional[str]:
    """'requests' for requests.post(...), 'time' for time.sleep(...);
    walks chains ('urllib' for urllib.request.urlopen)."""
    node = call.func
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _blocking_kind(idx: index_lib.PackageIndex, rel: str,
                   call: ast.Call) -> Optional[str]:
    callee = idx.callee_name(call)
    if callee is None:
        return None
    if isinstance(call.func, ast.Name):
        if callee in _BLOCKING_BARE_CALLS:
            return f'{callee}() file I/O'
        # `from time import sleep`-style direct imports.
        mod = idx.modules[rel].from_imports.get(callee)
        if mod is not None:
            base, attr = mod[0].split('.')[0], mod[1]
            allowed = _BLOCKING_MODULE_CALLS.get(base)
            if allowed is None and base in _BLOCKING_MODULE_CALLS:
                return f'{base}.{attr}()'
            if allowed and attr in allowed:
                return f'{base}.{attr}()'
        return None
    if callee in _BLOCKING_ATTR_CALLS:
        return f'.{callee}() device sync'
    base = _call_base_name(call)
    if base is not None:
        # Resolve `req_lib.post` style aliases back to the module.
        dotted = idx.modules[rel].import_aliases.get(base, base)
        top = dotted.split('.')[0]
        allowed = _BLOCKING_MODULE_CALLS.get(top)
        if top in _BLOCKING_MODULE_CALLS and (
                allowed is None or callee in allowed):
            return f'{top}.{callee}()'
    if (callee == 'append' and
            isinstance(call.func, ast.Attribute) and
            _is_journalish(call.func.value)):
        return 'journal append (file I/O)'
    return None


class _FnWalker(ast.NodeVisitor):
    """Walks one function body tracking the held-lock stack."""

    def __init__(self, idx: index_lib.PackageIndex, rel: str,
                 cls: Optional[index_lib.ClassInfo],
                 method_name: str,
                 locks: Dict[str, Lock],
                 module_locks: Dict[str, str],
                 attr_types: Dict[Tuple[str, str],
                                  Tuple[str, str]]) -> None:
        self.idx = idx
        self.rel = rel
        self.cls = cls
        self.method_name = method_name
        self.locks = locks
        self.module_locks = module_locks
        self.attr_types = attr_types
        self.local_locks: Dict[str, str] = {}
        self.held: List[str] = []
        self.facts = _FnFacts(
            key=(rel, (f'{cls.name}.{method_name}' if cls
                       else method_name)),
            acquires=set(), blocking=[], callees=set(), edges=[],
            held_calls=[], held_blocking=[], attr_writes={})

    # -------------------------------------------------- lock identity

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if (isinstance(expr, ast.Attribute) and
                isinstance(expr.value, ast.Name) and
                expr.value.id == 'self' and self.cls is not None and
                expr.attr in self.cls.lock_attrs):
            return f'{self.rel}::{self.cls.name}.{expr.attr}'
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                return self.local_locks[expr.id]
            if expr.id in self.module_locks:
                return self.module_locks[expr.id]
        return None

    # ------------------------------------------------------- visitors

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # Nested defs execute later with an empty held stack; their
        # bodies are still part of this function's facts (closures run
        # on the same objects), so walk them with the stack cleared.
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _enter_locks(self, node) -> List[str]:
        entered: List[str] = []
        for item in node.items:
            lock = self._lock_of(item.context_expr)
            if lock is None:
                continue
            self.facts.acquires.add(lock)
            for held in self.held:
                self.facts.edges.append((held, lock,
                                         item.context_expr.lineno))
            self.held.append(lock)
            entered.append(lock)
        return entered

    def visit_With(self, node: ast.With) -> None:
        entered = self._enter_locks(node)
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        for tgt in node.targets:
            if (isinstance(tgt, ast.Name) and
                    index_lib._is_lock_factory(value)):
                self.local_locks[tgt.id] = (
                    f'{self.rel}::{self.facts.key[1]}.{tgt.id}')
            self._record_attr_write(tgt, node.lineno)
        self.visit(value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_attr_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._record_attr_write(node.target, node.lineno)
        if node.value is not None:
            self.visit(node.value)

    def _record_attr_write(self, tgt: ast.AST, line: int) -> None:
        if (isinstance(tgt, ast.Attribute) and
                isinstance(tgt.value, ast.Name) and
                tgt.value.id == 'self' and self.cls is not None):
            self.facts.attr_writes.setdefault(tgt.attr, []).append(
                (line, bool(self.held)))

    def visit_Call(self, node: ast.Call) -> None:
        callee_key = self._resolve_callee(node)
        if callee_key is not None:
            self.facts.callees.add(callee_key)
            if self.held:
                self.facts.held_calls.append(
                    (tuple(self.held), node.lineno, callee_key))
        kind = None
        if not self._is_lock_releasing_wait(node):
            kind = _blocking_kind(self.idx, self.rel, node)
        if kind is not None:
            self.facts.blocking.append((node.lineno, kind))
            if self.held:
                self.facts.held_blocking.append(
                    (tuple(self.held), node.lineno, kind))
        self.generic_visit(node)

    def _is_lock_releasing_wait(self, call: ast.Call) -> bool:
        """cond.wait()/wait_for() releases the held condition lock."""
        return (isinstance(call.func, ast.Attribute) and
                call.func.attr in _LOCK_RELEASING_WAITS)

    def _resolve_callee(self, call: ast.Call) \
            -> Optional[Tuple[str, str]]:
        func = call.func
        if isinstance(func, ast.Name):
            key = (self.rel, func.id)
            return key if key in self.idx.functions else None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == 'self' and self.cls is not None:
                key = (self.rel, f'{self.cls.name}.{func.attr}')
                return key if key in self.idx.functions else None
            target = self.idx.resolve_module_alias(self.rel, base.id)
            if target is not None:
                key = (target, func.attr)
                return key if key in self.idx.functions else None
        if (isinstance(base, ast.Attribute) and
                isinstance(base.value, ast.Name) and
                base.value.id == 'self' and self.cls is not None):
            typed = self.attr_types.get((self.cls.name, base.attr))
            if typed is not None:
                key = (typed[0], f'{typed[1]}.{func.attr}')
                return key if key in self.idx.functions else None
        return None


def _module_locks(idx: index_lib.PackageIndex, rel: str) \
        -> Dict[str, Tuple[str, bool]]:
    """top-level `name = threading.Lock()` -> (lock_id, reentrant)."""
    out: Dict[str, Tuple[str, bool]] = {}
    for node in idx.modules[rel].tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not index_lib._is_lock_factory(node.value):
            continue
        factory = node.value.func
        name = (factory.attr if isinstance(factory, ast.Attribute)
                else factory.id)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = (f'{rel}::{tgt.id}',
                               name in _REENTRANT_FACTORIES)
    return out


def _lock_registry(idx: index_lib.PackageIndex) -> Dict[str, Lock]:
    """Every class-attr and module-global lock in the package."""
    locks: Dict[str, Lock] = {}
    for (rel, cname), cls in sorted(idx.classes.items()):
        for attr in cls.lock_attrs:
            reentrant = _attr_lock_reentrant(cls, attr)
            lid = f'{rel}::{cname}.{attr}'
            locks[lid] = Lock(lid, reentrant)
    for rel in sorted(idx.modules):
        for _, (lid, reentrant) in _module_locks(idx, rel).items():
            locks[lid] = Lock(lid, reentrant)
    return locks


def _attr_lock_reentrant(cls: index_lib.ClassInfo, attr: str) -> bool:
    for item in ast.walk(cls.node):
        if not isinstance(item, ast.Assign):
            continue
        if not index_lib._is_lock_factory(item.value):
            continue
        for tgt in item.targets:
            if (isinstance(tgt, ast.Attribute) and
                    tgt.attr == attr):
                factory = item.value.func
                name = (factory.attr
                        if isinstance(factory, ast.Attribute)
                        else factory.id)
                return name in _REENTRANT_FACTORIES
    return False


def _attr_types(idx: index_lib.PackageIndex, rel: str) \
        -> Dict[Tuple[str, str], Tuple[str, str]]:
    """(ClassName, attr) -> (rel, AttrClassName) for ``self.attr =
    SomeClass(...)`` / ``x or SomeClass(...)`` inits, intra-package."""
    out: Dict[Tuple[str, str], Tuple[str, str]] = {}

    def class_of(value: ast.AST) -> Optional[Tuple[str, str]]:
        if isinstance(value, ast.BoolOp):
            for operand in value.values:
                got = class_of(operand)
                if got is not None:
                    return got
            return None
        if not isinstance(value, ast.Call):
            return None
        func = value.func
        if isinstance(func, ast.Name):
            key = (rel, func.id)
            return key if key in idx.classes else None
        if (isinstance(func, ast.Attribute) and
                isinstance(func.value, ast.Name)):
            target = idx.resolve_module_alias(rel, func.value.id)
            if target is not None and \
                    (target, func.attr) in idx.classes:
                return (target, func.attr)
        return None

    for (crel, cname), cls in idx.classes.items():
        if crel != rel:
            continue
        for item in ast.walk(cls.node):
            if not isinstance(item, ast.Assign):
                continue
            got = class_of(item.value)
            if got is None:
                continue
            for tgt in item.targets:
                if (isinstance(tgt, ast.Attribute) and
                        isinstance(tgt.value, ast.Name) and
                        tgt.value.id == 'self'):
                    out[(cname, tgt.attr)] = got
    return out


class ConcurrencyPass(core.Pass):

    name = 'concurrency'
    rules = ('lock-order', 'blocking-under-lock', 'unlocked-attr')
    description = ('lock-acquisition cycle detection, blocking calls '
                   'under a held lock, attributes with inconsistent '
                   'lock guards')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        locks = _lock_registry(idx)
        facts: Dict[Tuple[str, str], _FnFacts] = {}
        for rel in sorted(idx.modules):
            module_locks = {name: lid for name, (lid, _)
                            in _module_locks(idx, rel).items()}
            attr_types = _attr_types(idx, rel)
            for (frel, qual), fn in sorted(idx.functions.items()):
                if frel != rel:
                    continue
                cls = None
                method = qual
                if '.' in qual:
                    cname, method = qual.split('.', 1)
                    cls = idx.classes.get((rel, cname))
                walker = _FnWalker(idx, rel, cls, method, locks,
                                   module_locks, attr_types)
                node = fn.node
                for stmt in getattr(node, 'body', []):
                    walker.visit(stmt)
                facts[(rel, qual)] = walker.facts

        # ---- fixpoint: transitive lock sets + blocking-ness.
        all_locks: Dict[Tuple[str, str], Set[str]] = {
            k: set(f.acquires) for k, f in facts.items()}
        blocks: Dict[Tuple[str, str], bool] = {
            k: bool(f.blocking) for k, f in facts.items()}
        changed = True
        while changed:
            changed = False
            for k, f in facts.items():
                for callee in f.callees:
                    if callee not in facts:
                        continue
                    extra = all_locks[callee] - all_locks[k]
                    if extra:
                        all_locks[k] |= extra
                        changed = True
                    if blocks[callee] and not blocks[k]:
                        blocks[k] = True
                        changed = True

        # ---- edges: direct nesting + locks acquired via calls.
        edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
        for (rel, _), f in sorted(facts.items()):
            for held, acquired, line in f.edges:
                edges.setdefault((held, acquired), []).append(
                    (rel, line))
            for held_stack, line, callee in f.held_calls:
                for acquired in sorted(all_locks.get(callee, ())):
                    for held in held_stack:
                        edges.setdefault((held, acquired),
                                         []).append((rel, line))

        yield from self._cycle_findings(locks, edges)

        # ---- blocking under lock (direct + via callee).
        for (rel, qual), f in sorted(facts.items()):
            for held_stack, line, kind in f.held_blocking:
                yield core.Finding(
                    'blocking-under-lock', rel, line,
                    f'{kind} while holding '
                    f'{_short(held_stack[-1])} (in {qual})')
            for held_stack, line, callee in f.held_calls:
                if blocks.get(callee):
                    yield core.Finding(
                        'blocking-under-lock', rel, line,
                        f'call to {callee[1]} (which does blocking '
                        f'I/O) while holding '
                        f'{_short(held_stack[-1])} (in {qual})')

        # ---- unlocked-attr.
        writes: Dict[Tuple[str, str, str],
                     Dict[bool, List[Tuple[str, int]]]] = {}
        for (rel, qual), f in sorted(facts.items()):
            if '.' not in qual:
                continue
            cname, method = qual.split('.', 1)
            cls = idx.classes.get((rel, cname))
            if cls is None or not cls.lock_attrs:
                continue
            if method in ('__init__', '__post_init__'):
                continue
            for attr, sites in f.attr_writes.items():
                if attr in cls.lock_attrs:
                    continue
                slot = writes.setdefault((rel, cname, attr),
                                         {True: [], False: []})
                for line, locked in sites:
                    slot[locked].append((method, line))
        for (rel, cname, attr), slot in sorted(writes.items()):
            if slot[True] and slot[False]:
                method, line = slot[False][0]
                locked_method, locked_line = slot[True][0]
                yield core.Finding(
                    'unlocked-attr', rel, line,
                    f'{cname}.{attr} is written lock-free in '
                    f'{method} (line {line}) but under a lock in '
                    f'{locked_method} (line {locked_line}) — pick '
                    f'one guard')

    def _cycle_findings(self, locks: Dict[str, Lock],
                        edges: Dict[Tuple[str, str],
                                    List[Tuple[str, int]]]) \
            -> Iterator[core.Finding]:
        # Self-edges: re-acquiring a non-reentrant lock while held is
        # an unconditional deadlock, no cycle search needed.
        graph: Dict[str, Set[str]] = {}
        for (a, b), sites in sorted(edges.items()):
            if a == b:
                lock = locks.get(a)
                if lock is None or lock.reentrant:
                    continue
                rel, line = sorted(sites)[0]
                yield core.Finding(
                    'lock-order', rel, line,
                    f'non-reentrant {_short(a)} re-acquired while '
                    f'already held — unconditional deadlock')
                continue
            graph.setdefault(a, set()).add(b)
        # Cross-lock cycles: report every edge inside a strongly
        # connected component.
        for component in _sccs(graph):
            if len(component) < 2:
                continue
            members = set(component)
            order = ' -> '.join(_short(lid)
                                for lid in sorted(members))
            for (a, b), sites in sorted(edges.items()):
                if a in members and b in members and a != b:
                    rel, line = sorted(sites)[0]
                    yield core.Finding(
                        'lock-order', rel, line,
                        f'lock-order cycle [{order}]: {_short(a)} '
                        f'held while acquiring {_short(b)} here, and '
                        f'the reverse order exists elsewhere')


def _short(lock_id: str) -> str:
    return lock_id.split('::', 1)[-1]


def _sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan, iterative enough for our graph sizes (recursion fine:
    lock graphs are tiny)."""
    indices: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        indices[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in indices:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], indices[w])
        if low[v] == indices[v]:
            comp: List[str] = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    for v in sorted(set(graph) |
                    {w for ws in graph.values() for w in ws}):
        if v not in indices:
            strongconnect(v)
    return out
