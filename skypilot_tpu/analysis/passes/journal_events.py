"""`journal-*`: every journal event name the code can emit is in the
docs/observability.md vocabulary table, and vice versa.

The flight-recorder journals (observability/events.py) are the
verification substrate for the chaos invariants — an event the docs
don't name is telemetry nobody can replay deliberately, and a
documented event nothing emits is a vocabulary lie.  Emission sites
come in three shapes, all resolved from the ASTs:

1. **direct appends** — ``<journalish>.append('name', ...)`` where the
   receiver expression mentions ``journal`` (``journal.append``,
   ``self._journal.append``, ``chaos_journal().append``,
   ``events_lib.get_journal(...).append``).  List ``.append`` never
   matches: lists aren't named journal.
2. **wrappers** — a function whose body forwards its own first
   parameter into a journalish append (``def _journal_drain(event,
   **f): _serve_journal().append(event, **f)``); its call sites with a
   string-literal first argument emit that name.  A wrapper appending
   ``f'{param}_start'`` emits ``<literal>_start`` per call site.
3. **ControlSpan** — ``ControlSpan(journal, 'name')`` (and a
   journalish ``.span('name')``) emits ``name_start`` + ``name_end``.

A name argument that is a local variable resolves when every
module-level assignment to it is a literal (or a conditional between
literals: ``'launch' if ... else 'exec'``).  Anything else is its own
`journal-computed-name` finding: make it a literal, or suppress with
a reason naming the events it can produce — and document those.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib
from skypilot_tpu.analysis.passes import metrics_catalog

_DOC = 'observability.md'
_SECTION = '### Journal event vocabulary'
_EVENT_RE = re.compile(r'`([a-z][a-z0-9_]*)`')


def _is_journalish(expr: ast.AST) -> bool:
    try:
        text = ast.unparse(expr)
    except Exception:  # pylint: disable=broad-except
        return False
    return 'journal' in text.lower()


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fstring_suffix(node: ast.AST) -> Optional[Tuple[str, str]]:
    """``f'{name}_start'`` -> ('name', '_start'); None otherwise."""
    if not isinstance(node, ast.JoinedStr) or len(node.values) != 2:
        return None
    fmt, tail = node.values
    if not (isinstance(fmt, ast.FormattedValue) and
            isinstance(fmt.value, ast.Name)):
        return None
    suffix = _literal_str(tail)
    if suffix is None:
        return None
    return fmt.value.id, suffix


def _resolve_literals(arg: ast.AST,
                      mod: index_lib.ModuleInfo) -> Optional[List[str]]:
    """Possible literal values of an event-name argument: a literal, a
    conditional between literals, or a variable whose every assignment
    in the module is one of those.  None = computed."""
    lit = _literal_str(arg)
    if lit is not None:
        return [lit]
    if isinstance(arg, ast.IfExp):
        body = _resolve_literals(arg.body, mod)
        orelse = _resolve_literals(arg.orelse, mod)
        if body is not None and orelse is not None:
            return sorted(set(body + orelse))
        return None
    if isinstance(arg, ast.Name):
        values: List[str] = []
        assigned = False
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == arg.id
                       for t in node.targets):
                continue
            assigned = True
            sub = _resolve_literals(node.value, mod)
            if sub is None:
                return None
            values.extend(sub)
        return sorted(set(values)) if assigned else None
    return None


class _Emitter:
    """How a wrapper's first argument maps to event names: the name
    itself (suffixes None) or ``<name><suffix>`` per suffix."""

    def __init__(self, param: str,
                 suffixes: Optional[List[str]] = None) -> None:
        self.param = param
        self.suffixes = suffixes


@dataclasses.dataclass
class EmitSite:
    """One resolved journal-emission call site.

    `names` is the list of event names the site can emit (None =
    computed/unresolvable — a `journal-computed-name` finding).
    `kind` records the mechanism: 'append' (direct journalish append),
    'span' (ControlSpan / journalish .span — the context manager
    guarantees the `_end`), or 'wrapper' (a call through a journaling
    wrapper function).  `func` is the enclosing function's index key,
    `call` the AST call node — the journal-protocol pass uses both to
    check finally/except coverage of `_start` emits.
    """
    rel: str
    line: int
    func: Tuple[str, str]
    call: ast.Call
    names: Optional[List[str]]
    kind: str
    what: str      # message prefix for computed-name findings


def collect_emit_sites(idx: index_lib.PackageIndex) -> List[EmitSite]:
    """Every journal-emission call site in the package, in the
    deterministic (sorted functions, AST walk) order."""
    sites: List[EmitSite] = []

    # ---- pass 1: wrapper functions (first param -> journal append).
    # The append nodes that *define* a wrapper are remembered so pass 2
    # does not re-flag them as computed names.
    wrappers: Dict[Tuple[str, str], _Emitter] = {}
    wrapper_sinks: Set[int] = set()
    for (rel, qual), fn in sorted(idx.functions.items()):
        node = fn.node
        args = node.args
        params = [a.arg for a in args.posonlyargs + args.args
                  if a.arg not in ('self', 'cls')]
        if not params:
            continue
        first = params[0]
        suffixes: List[str] = []
        direct = False
        sinks: List[int] = []
        for call in idx.iter_calls(node):
            if (idx.callee_name(call) != 'append' or not call.args or
                    not isinstance(call.func, ast.Attribute) or
                    not _is_journalish(call.func.value)):
                continue
            arg0 = call.args[0]
            if isinstance(arg0, ast.Name) and arg0.id == first:
                direct = True
                sinks.append(id(call))
            else:
                fs = _fstring_suffix(arg0)
                if fs is not None and fs[0] == first:
                    suffixes.append(fs[1])
                    sinks.append(id(call))
        if direct or suffixes:
            wrappers[(rel, qual)] = _Emitter(
                first, None if direct else sorted(set(suffixes)))
            wrapper_sinks.update(sinks)

    def emit_arg(arg: ast.AST, em: Optional[_Emitter], rel: str,
                 func: Tuple[str, str], call: ast.Call,
                 mod: index_lib.ModuleInfo, kind: str,
                 what: str) -> None:
        lits = _resolve_literals(arg, mod)
        if lits is None:
            sites.append(EmitSite(rel, call.lineno, func, call, None,
                                  kind, what))
            return
        suffixes = em.suffixes if em is not None else None
        names: List[str] = []
        for lit in lits:
            if suffixes is None:
                names.append(lit)
            else:
                names.extend(lit + sfx for sfx in suffixes)
        sites.append(EmitSite(rel, call.lineno, func, call, names,
                              kind, what))

    # ---- pass 2: every call site, walked per function so self-calls
    # resolve against the ENCLOSING class (a `_record` wrapper in one
    # class must not capture `self._record` of another).
    for (rel, qual), fn in sorted(idx.functions.items()):
        mod = idx.modules[rel]
        func = (rel, qual)
        cls_name = qual.split('.', 1)[0] if '.' in qual else None
        for call in idx.iter_calls(fn.node):
            callee = idx.callee_name(call)
            if callee == 'append':
                if (id(call) in wrapper_sinks or not call.args or
                        not isinstance(call.func, ast.Attribute) or
                        not _is_journalish(call.func.value)):
                    continue
                fs = _fstring_suffix(call.args[0])
                if fs is not None:
                    # f'{x}_start' outside a wrapper: resolve x from
                    # module assignments.
                    lits = _resolve_literals(
                        ast.Name(id=fs[0], ctx=ast.Load()), mod)
                    if lits is None:
                        sites.append(EmitSite(
                            rel, call.lineno, func, call, None,
                            'append', 'journal append'))
                    else:
                        sites.append(EmitSite(
                            rel, call.lineno, func, call,
                            [lit + fs[1] for lit in lits], 'append',
                            'journal append'))
                    continue
                emit_arg(call.args[0], None, rel, func, call, mod,
                         'append', 'journal append')
            elif callee == 'ControlSpan':
                if len(call.args) < 2:
                    continue
                emit_arg(call.args[1], _Emitter('', ['_start', '_end']),
                         rel, func, call, mod, 'span', 'ControlSpan')
            elif callee == 'span':
                if (not call.args or
                        not isinstance(call.func, ast.Attribute) or
                        not _is_journalish(call.func.value)):
                    continue
                emit_arg(call.args[0], _Emitter('', ['_start', '_end']),
                         rel, func, call, mod, 'span', 'journal span')
            elif callee is not None:
                em = None
                if isinstance(call.func, ast.Name):
                    em = wrappers.get((rel, callee))
                elif (isinstance(call.func, ast.Attribute) and
                      isinstance(call.func.value, ast.Name)):
                    base = call.func.value.id
                    if base == 'self' and cls_name is not None:
                        em = wrappers.get((rel,
                                           f'{cls_name}.{callee}'))
                    else:
                        # module-alias call into another module's
                        # wrapper (controller.py journaling through
                        # replica_managers._journal_drain).
                        target = idx.resolve_module_alias(rel, base)
                        if target is not None:
                            em = wrappers.get((target, callee))
                if em is None or not call.args:
                    continue
                emit_arg(call.args[0], em, rel, func, call, mod,
                         'wrapper', f'{callee}()')
    return sites


def collect_events(idx: index_lib.PackageIndex) \
        -> Tuple[Dict[str, List[Tuple[str, int]]],
                 List[Tuple[str, int, str]]]:
    """(event -> [(file, line)], [(file, line, why)] computed names)."""
    events: Dict[str, List[Tuple[str, int]]] = {}
    computed: List[Tuple[str, int, str]] = []
    for site in collect_emit_sites(idx):
        if site.names is None:
            computed.append((site.rel, site.line,
                             f'{site.what} event name is not '
                             f'resolvable to string literals'))
        else:
            for name in site.names:
                events.setdefault(name, []).append(
                    (site.rel, site.line))
    return events, computed


def documented_events(doc_dir) -> Set[str]:
    """Backticked event names in the FIRST cell of the vocabulary
    section's table rows (prose in other cells never registers)."""
    doc = (doc_dir / _DOC).read_text(encoding='utf-8')
    names: Set[str] = set()
    in_section = False
    for line in doc.splitlines():
        if line.startswith('#'):
            in_section = line.strip() == _SECTION
            continue
        if in_section and line.startswith('|'):
            cells = line.split('|')
            if len(cells) >= 2:
                names.update(_EVENT_RE.findall(cells[1]))
    return names


class JournalEventsPass(core.Pass):

    name = 'journal-events'
    rules = ('journal-undocumented', 'journal-stale-doc',
             'journal-computed-name')
    description = ('journal event vocabulary matches '
                   'docs/observability.md, both directions; computed '
                   'event names flagged')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        doc_dir = metrics_catalog.docs_root(idx)
        if doc_dir is None or not (doc_dir / _DOC).is_file():
            return
        events, computed = collect_events(idx)
        for rel, line, why in sorted(set(computed)):
            yield core.Finding('journal-computed-name', rel, line, why)
        documented = documented_events(doc_dir)
        for name in sorted(set(events) - documented):
            rel, line = events[name][0]
            yield core.Finding(
                'journal-undocumented', rel, line,
                f'journal event {name!r} is not in the docs/{_DOC} '
                f'vocabulary table (add a row)')
        for name in sorted(documented - set(events)):
            yield core.Finding(
                'journal-stale-doc', 'observability/events.py', 0,
                f'docs/{_DOC} vocabulary names event {name!r} that '
                f'no code emits (delete the row or restore the '
                f'emitter)')
