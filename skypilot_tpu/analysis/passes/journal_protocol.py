"""`journal-protocol-*`: paired journal events fit the machine-readable
protocol table and every `_start` has a guaranteed `_end`.

The paired-event lifecycles (drain_start/_end, kv_handoff, kv_pages
alloc/free, ControlSpan spans, ...) live in ONE table —
`observability/event_protocol.py` — shared by the chaos invariant
checkers (which replay journals at runtime) and this pass (which
verifies the emit sites statically).  The table is read from the
analyzed package's AST, never imported: lint stays AST-only.

Checks:

- **journal-protocol-unregistered** — an emitted event named like a
  lifecycle (`*_start` / `*_end`) whose base is not a table row.  New
  lifecycles must register, or the invariants can never replay them.
- **journal-protocol-stale** — a table row whose start or end event no
  code emits (the lifecycle is a vocabulary lie).
- **journal-unguarded-start** — an invocation-scoped lifecycle whose
  `_start` is emitted by a function that does not guarantee the `_end`
  on exception paths: the matching end emit must sit in a `finally`
  or `except` block of the same function.  ControlSpan/`.span()` call
  sites are exempt — the context manager's `__exit__` IS the
  guarantee.  Process-scoped lifecycles (state machines like
  replica_drain or slo_burn) are exempt; only journal replay can
  check those.
- **journal-protocol-status** — an end emit whose literal
  status/reason value is outside the table's allowed terminal set
  (the same set the invariants enforce at replay time): a typo'd
  status would pass the emitter and fail every future chaos run.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.analysis import core
from skypilot_tpu.analysis import index as index_lib
from skypilot_tpu.analysis.passes import journal_events

PROTOCOL_MODULE = 'observability/event_protocol.py'


class PairSpec:
    """One protocol-table row, as parsed from the AST."""

    def __init__(self, name: str, start: str, end: str, scope: str,
                 status_field: Optional[str],
                 statuses: Optional[Tuple[str, ...]]) -> None:
        self.name = name
        self.start = start
        self.end = end
        self.scope = scope
        self.status_field = status_field
        self.statuses = statuses


def _literal(node: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def load_protocol(idx: index_lib.PackageIndex) -> List[PairSpec]:
    """Parse the PAIRS table out of the protocol module's AST.

    Rows are `_pair(name, scope, ...)` / `PairedEvents(...)` calls
    inside the module-level `PAIRS = (...)` assignment; module-level
    string constants (the SCOPE_* names) resolve as arguments."""
    mod = idx.modules.get(PROTOCOL_MODULE)
    if mod is None:
        return []
    consts: Dict[str, str] = {}
    pairs_node: Optional[ast.AST] = None
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # PAIRS: Tuple[...] = ..
            targets = [node.target]
        else:
            continue
        for tgt in targets:
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == 'PAIRS':
                pairs_node = node.value
            elif (isinstance(node.value, ast.Constant) and
                  isinstance(node.value.value, str)):
                consts[tgt.id] = node.value.value
    if pairs_node is None or not isinstance(pairs_node,
                                            (ast.Tuple, ast.List)):
        return []
    out: List[PairSpec] = []
    for elt in pairs_node.elts:
        if not isinstance(elt, ast.Call):
            continue
        pos = [_literal(a, consts) for a in elt.args]
        kw: Dict[str, ast.AST] = {k.arg: k.value
                                  for k in elt.keywords if k.arg}
        name = pos[0] if pos else _literal(kw.get('name'), consts)
        scope = (pos[1] if len(pos) > 1
                 else _literal(kw.get('scope'), consts))
        if name is None or scope is None:
            continue
        start = _literal(kw.get('start'), consts) or f'{name}_start'
        end = _literal(kw.get('end'), consts) or f'{name}_end'
        status_field = _literal(kw.get('status_field'), consts)
        statuses: Optional[Tuple[str, ...]] = None
        st = kw.get('statuses')
        if isinstance(st, (ast.Tuple, ast.List)):
            vals = [_literal(e, consts) for e in st.elts]
            if all(v is not None for v in vals):
                statuses = tuple(vals)  # type: ignore[arg-type]
        out.append(PairSpec(name, start, end, scope, status_field,
                            statuses))
    return out


def _guard_nodes(fn: ast.AST) -> List[ast.AST]:
    """Every statement living under a `finally:` or `except:` of the
    function — the regions where an end-emit is exception-guaranteed."""
    guarded: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                guarded.extend(ast.walk(stmt))
            for handler in node.handlers:
                for stmt in handler.body:
                    guarded.extend(ast.walk(stmt))
    return guarded


class JournalProtocolPass(core.Pass):

    name = 'journal-protocol'
    rules = ('journal-protocol-unregistered', 'journal-protocol-stale',
             'journal-unguarded-start', 'journal-protocol-status')
    description = ('paired journal events match the event_protocol '
                   'table; _start emits guarantee their _end on '
                   'exception paths; terminal statuses are from the '
                   'allowed set')

    def run(self, idx: index_lib.PackageIndex) \
            -> Iterator[core.Finding]:
        pairs = load_protocol(idx)
        if not pairs:
            return
        by_start = {p.start: p for p in pairs}
        by_end = {p.end: p for p in pairs}
        sites = journal_events.collect_emit_sites(idx)

        emitted: Dict[str, Tuple[str, int]] = {}
        for site in sites:
            for name in site.names or ():
                emitted.setdefault(name, (site.rel, site.line))

        # Unregistered lifecycles: the _start/_end naming convention IS
        # the registration trigger (asymmetric pairs like rank_exit or
        # kv_pages_alloc/free register through their table row).
        registered = set(by_start) | set(by_end)
        for name in sorted(emitted):
            if not (name.endswith('_start') or name.endswith('_end')):
                continue
            if name in registered:
                continue
            rel, line = emitted[name]
            yield core.Finding(
                'journal-protocol-unregistered', rel, line,
                f'paired event {name!r} is not in the '
                f'{PROTOCOL_MODULE} protocol table — register the '
                f'lifecycle (scope + terminal statuses) so the chaos '
                f'invariants can replay it')

        for p in pairs:
            for which, event in (('start', p.start), ('end', p.end)):
                if event not in emitted:
                    yield core.Finding(
                        'journal-protocol-stale', PROTOCOL_MODULE, 0,
                        f'protocol table row {p.name!r} names {which} '
                        f'event {event!r} that no code emits — delete '
                        f'the row or restore the emitter')

        # Guard check: invocation-scoped starts emitted by a direct
        # append/wrapper need a finally/except end in the SAME function.
        for site in sites:
            if site.kind == 'span' or site.names is None:
                continue
            for name in site.names:
                p = by_start.get(name)
                if p is None or p.scope != 'invocation':
                    continue
                if self._guarded(idx, sites, site, p):
                    continue
                yield core.Finding(
                    'journal-unguarded-start', site.rel, site.line,
                    f'{p.start!r} is emitted without a guaranteed '
                    f'{p.end!r} on exception paths — emit the end '
                    f'from a finally/except in this function (or use '
                    f'ControlSpan), else a crash here reads as a '
                    f'lifecycle that never terminated')

        # Terminal-status check at end-emit sites.
        for site in sites:
            if site.names is None:
                continue
            for name in site.names:
                p = by_end.get(name)
                if p is None or not p.statuses or not p.status_field:
                    continue
                if site.kind == 'span':
                    continue  # ControlSpan stamps 'ok'/<exc name>
                for kwarg in site.call.keywords:
                    if kwarg.arg != p.status_field:
                        continue
                    value = kwarg.value
                    if isinstance(value, ast.Constant) and \
                            isinstance(value.value, str) and \
                            value.value not in p.statuses:
                        yield core.Finding(
                            'journal-protocol-status', site.rel,
                            site.line,
                            f'{p.end!r} emitted with '
                            f'{p.status_field}={value.value!r}, not an '
                            f'allowed terminal status '
                            f'({"/".join(p.statuses)}) — the chaos '
                            f'invariants will reject it at replay')

    @staticmethod
    def _guarded(idx: index_lib.PackageIndex,
                 sites: List[journal_events.EmitSite],
                 start_site: journal_events.EmitSite,
                 p: PairSpec) -> bool:
        fn = idx.functions.get(start_site.func)
        if fn is None:
            return False
        # __enter__ emitting the start with the end in the same class's
        # __exit__ IS the context-manager guarantee (ControlSpan-style
        # implementations).
        qual = start_site.func[1]
        if qual.endswith('.__enter__'):
            cls = qual.rsplit('.', 1)[0]
            exit_key = (start_site.func[0], f'{cls}.__exit__')
            for other in sites:
                if other.func == exit_key and p.end in (other.names
                                                        or ()):
                    return True
        guarded_ids = {id(n) for n in _guard_nodes(fn.node)}
        for other in sites:
            if other.func != start_site.func:
                continue
            if p.end not in (other.names or ()):
                continue
            if id(other.call) in guarded_ids:
                return True
        return False
