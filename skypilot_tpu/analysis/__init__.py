"""Static-analysis plane: `sky lint` (ISSUE 12).

One parse of the whole package into a shared :class:`~skypilot_tpu.
analysis.index.PackageIndex` (ASTs, per-module import-alias maps,
per-class attribute tables, a lightweight call graph), then pluggable
checker passes over it producing ``(rule_id, file, line, message)``
findings.  Three layers:

- `analysis/index.py`  — the parse-once package index.  AST only: the
  analyzed modules are never imported, so a lint run cannot execute
  package code (and runs in seconds on CPU).
- `analysis/core.py`   — Finding / Pass / the runner: inline
  suppressions (``# skytpu: lint-ok[rule] reason=...`` — the reason is
  mandatory), the committed baseline for grandfathered findings
  (`lint-baseline.json`, stale entries are themselves findings), and
  deterministic JSON output.
- `analysis/passes/`   — the checker passes (rule catalog in
  docs/static-analysis.md): the concurrency race detector, the JAX
  tracer-safety pass, the env-knob / journal-event / metrics-catalog
  registries, the chaos-site and bare-print lints (migrated from
  their ad-hoc test walkers), and the batching-engine facade-surface
  check.

Surfaced as ``skytpu lint [--rule ...] [--json]`` (exit 1 on
unsuppressed findings) and the tier-1 `tests/unit/test_sky_lint.py`
run over the repo itself.
"""
from skypilot_tpu.analysis.core import Finding
from skypilot_tpu.analysis.core import LintResult
from skypilot_tpu.analysis.core import Pass
from skypilot_tpu.analysis.core import run_lint
from skypilot_tpu.analysis.index import PackageIndex

__all__ = ['Finding', 'LintResult', 'Pass', 'PackageIndex', 'run_lint']
