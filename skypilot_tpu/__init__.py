"""skypilot_tpu: a TPU-native orchestration framework.

Capability parity with SkyPilot (/root/reference/sky/__init__.py:139 __all__)
rebuilt TPU-first: slices are the atomic resource, gangs are implicit in
topology, and the job contract hands user code a ready JAX distributed
environment instead of raw IP lists.
"""
from __future__ import annotations

__version__ = '0.3.0'

from skypilot_tpu import clouds
from skypilot_tpu import jobs
from skypilot_tpu import serve
from skypilot_tpu.check import check
from skypilot_tpu.data.storage import Storage
from skypilot_tpu.data.storage import StorageMode
from skypilot_tpu.data.storage import StoreType
from skypilot_tpu.core import autostop
from skypilot_tpu.core import cancel
from skypilot_tpu.core import cost_report
from skypilot_tpu.core import down
from skypilot_tpu.core import download_logs
from skypilot_tpu.core import endpoints
from skypilot_tpu.core import job_status
from skypilot_tpu.core import queue
from skypilot_tpu.core import start
from skypilot_tpu.core import status
from skypilot_tpu.core import stop
from skypilot_tpu.core import storage_delete
from skypilot_tpu.core import storage_ls
from skypilot_tpu.core import tail_logs
from skypilot_tpu.dag import Dag
from skypilot_tpu.execution import exec  # pylint: disable=redefined-builtin
from skypilot_tpu.execution import launch
from skypilot_tpu.optimizer import Optimizer
from skypilot_tpu.optimizer import OptimizeTarget
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

GCP = clouds.GCP
GKE = clouds.GKE
Local = clouds.Local

__all__ = [
    '__version__',
    'Dag',
    'GCP',
    'GKE',
    'Local',
    'Optimizer',
    'OptimizeTarget',
    'Resources',
    'Storage',
    'StorageMode',
    'StoreType',
    'Task',
    'autostop',
    'cancel',
    'check',
    'cost_report',
    'down',
    'download_logs',
    'endpoints',
    'exec',
    'job_status',
    'jobs',
    'launch',
    'optimize',
    'queue',
    'serve',
    'start',
    'status',
    'stop',
    'storage_delete',
    'storage_ls',
    'tail_logs',
]

# `sky.optimize(dag)` parity (reference sky/__init__.py exports the
# Optimizer entry point as a top-level verb).
optimize = Optimizer.optimize
