"""Stage-runtime recording: where did my launch time go?

Parity: /root/reference/sky/usage/usage_lib.py:66,265 (`UsageMessage...
update_runtime` records per-stage wall clock) — minus the phone-home:
the reference POSTs usage messages to a Loki endpoint; here records
stay on the user's machine (JSONL under $SKYTPU_HOME/usage/) and feed
`sky status` / `sky cost-report`.  Time-to-first-step is the declared
north-star denominator (BASELINE.md), so its decomposition
(optimize/provision/sync/setup/exec-submit) must be visible for every
launch.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
import uuid
from typing import Any, Dict, Iterator, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# Stages whose wall-clock sums to "time to first step" (everything
# between the user's command and their code running on the slice).
TTFS_STAGES = ('optimize', 'provision', 'sync_workdir',
               'sync_file_mounts', 'setup', 'pre_exec', 'exec_submit')


def _usage_dir() -> str:
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    return common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'usage'))


def _runs_path() -> str:
    return os.path.join(_usage_dir(), 'runs.jsonl')


class RunRecord:
    """One launch/exec invocation's stage decomposition."""

    def __init__(self, entrypoint: str,
                 cluster_name: Optional[str] = None) -> None:
        self.run_id = uuid.uuid4().hex[:12]
        self.entrypoint = entrypoint
        self.cluster_name = cluster_name
        self.started_at = time.time()
        self.stage_runtimes: Dict[str, float] = {}
        self._finalized = False

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.stage_runtimes[name] = round(
                self.stage_runtimes.get(name, 0.0) +
                time.perf_counter() - t0, 3)

    @property
    def time_to_first_step(self) -> float:
        return round(sum(self.stage_runtimes.get(s, 0.0)
                         for s in TTFS_STAGES), 3)

    def to_dict(self) -> Dict[str, Any]:
        return {
            'run_id': self.run_id,
            'entrypoint': self.entrypoint,
            'cluster_name': self.cluster_name,
            'started_at': self.started_at,
            'stage_runtimes': dict(self.stage_runtimes),
            'time_to_first_step': self.time_to_first_step,
        }

    def finalize(self) -> None:
        """Append to the JSONL store (idempotent)."""
        if self._finalized:
            return
        self._finalized = True
        try:
            with open(_runs_path(), 'a', encoding='utf-8') as f:
                f.write(json.dumps(self.to_dict()) + '\n')
        except OSError as e:
            logger.debug(f'usage record append failed: {e}')


def records(limit: Optional[int] = None) -> list:
    """All run records, oldest first."""
    try:
        with open(_runs_path(), encoding='utf-8') as f:
            out = []
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return []
    return out[-limit:] if limit else out


def latest_launches() -> Dict[str, Dict[str, Any]]:
    """cluster_name -> most recent LAUNCH decomposition, in one file
    pass (status/cost_report call this once for all clusters instead of
    re-parsing the JSONL per record)."""
    out: Dict[str, Dict[str, Any]] = {}
    for rec in records():
        if rec.get('entrypoint') == 'launch' and rec.get('cluster_name'):
            out[rec['cluster_name']] = rec
    return out


def latest_for_cluster(cluster_name: str) -> Optional[Dict[str, Any]]:
    """The most recent LAUNCH decomposition for a cluster."""
    return latest_launches().get(cluster_name)


def format_decomposition(rec: Dict[str, Any]) -> str:
    """'total 12.3s = provision 8.1s + setup 2.0s + exec 0.4s + ...'"""
    runtimes = rec.get('stage_runtimes', {})
    parts = [f'{name} {runtimes[name]:.1f}s'
             for name in TTFS_STAGES if runtimes.get(name)]
    return (f'time-to-first-step {rec.get("time_to_first_step", 0):.1f}s'
            + (f' = {" + ".join(parts)}' if parts else ''))
