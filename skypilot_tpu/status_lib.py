"""Cluster / storage status enums.

Parity: /root/reference/sky/status_lib.py:1-51, extended with TPU
queued-resource states: a slice requested through the GCP queued-resources API
can sit in WAITING for minutes-to-days before the cloud fulfills it, which the
reference's {INIT, UP, STOPPED} model cannot express (SURVEY.md §7.4).
"""
from __future__ import annotations

import enum


class ClusterStatus(enum.Enum):
    """Lifecycle of a slice-cluster as recorded in local state."""
    # A launch has started but the slice is not fully up (or launch failed
    # midway). Also the state while provisioning/bootstrapping runs.
    INIT = 'INIT'
    # Queued-resource request submitted; waiting for the cloud to grant
    # capacity. New vs the reference (async provisioning).
    WAITING = 'WAITING'
    # All hosts of every slice are up and the runtime (skylet) is healthy.
    UP = 'UP'
    # Instances stopped but disks (and the queued-resource reservation,
    # where applicable) retained.
    STOPPED = 'STOPPED'

    def colored_str(self) -> str:
        color = {
            ClusterStatus.INIT: '\x1b[33m',     # yellow
            ClusterStatus.WAITING: '\x1b[36m',  # cyan
            ClusterStatus.UP: '\x1b[32m',       # green
            ClusterStatus.STOPPED: '\x1b[90m',  # gray
        }[self]
        return f'{color}{self.value}\x1b[0m'


class StorageStatus(enum.Enum):
    INIT = 'INIT'
    UPLOAD_FAILED = 'UPLOAD_FAILED'
    READY = 'READY'
