"""Pluggable org-level admin policy applied to every launch.

Parity: /root/reference/sky/admin_policy.py:1-101 +
utils/admin_policy_utils.py (validate_and_mutate hook loaded from config).
"""
from __future__ import annotations

import dataclasses
import importlib
import typing
from typing import Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions

if typing.TYPE_CHECKING:
    from skypilot_tpu import dag as dag_lib


@dataclasses.dataclass
class UserRequest:
    dag: 'dag_lib.Dag'


@dataclasses.dataclass
class MutatedUserRequest:
    dag: 'dag_lib.Dag'


class AdminPolicy:
    """Subclass and set config `admin_policy: my_module.MyPolicy`."""

    @classmethod
    def validate_and_mutate(cls,
                            user_request: UserRequest) -> MutatedUserRequest:
        return MutatedUserRequest(dag=user_request.dag)


def _load_policy() -> Optional[type]:
    path = config_lib.get_nested(('admin_policy',))
    if not path:
        return None
    module_name, _, class_name = path.rpartition('.')
    try:
        module = importlib.import_module(module_name)
        policy = getattr(module, class_name)
    except (ImportError, AttributeError) as e:
        raise exceptions.UserRequestRejectedByPolicy(
            f'Could not load admin policy {path!r}: {e}') from e
    if not issubclass(policy, AdminPolicy):
        raise exceptions.UserRequestRejectedByPolicy(
            f'{path!r} is not an AdminPolicy subclass.')
    return policy


def apply(dag: 'dag_lib.Dag') -> 'dag_lib.Dag':
    policy = _load_policy()
    if policy is None:
        return dag
    mutated = policy.validate_and_mutate(UserRequest(dag=dag))
    return mutated.dag
