"""Oracle Cloud (OCI): GPU VMs/bare-metal — a fifth fungible GPU pool.

Parity: /root/reference/sky/clouds/oci.py:1-633 (region/AD enumeration,
pricing, image + launch config, ~/.oci/config credential check) —
rebuilt on the oci CLI's JSON output with an injectable runner
(provision/oci/instance.py), the same no-SDK seam as aws/azure, minus
the reference's image-OCID resolution machinery (the provisioner takes
an image OCID directly or uses the platform default).

OCI placement is region + availability domain (the catalog's zone
column holds simplified AD names: AD-1..AD-3).  Instances live in one
compartment, configured via `oci.compartment_ocid` in the layered
config or the OCI_COMPARTMENT_OCID env var.
"""
from __future__ import annotations

import os
import subprocess
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class OCI(cloud_lib.Cloud):
    _REPR = 'OCI'
    PROVISIONER = 'oci'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not implemented for OCI.',
        cloud_lib.CloudImplementationFeatures.DOCKER_IMAGE:
            'Docker-image runtime is not implemented for OCI.',
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'Per-port ingress rides the VCN security list, not a '
            'per-instance API; configure the subnet instead.',
    }

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        if resources.tpu_spec is not None:
            return []  # TPUs are GCP-only.
        if resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'oci', resources.instance_type, resources.use_spot)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, zone_name in pairs:
            if (resources.region is not None and
                    region_name != resources.region):
                continue
            if resources.zone is not None and zone_name != resources.zone:
                continue
            region = regions.setdefault(region_name,
                                        cloud_lib.Region(region_name))
            region.zones.append(cloud_lib.Zone(zone_name, region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('oci', instance_type, use_spot,
                                       region, zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        del accelerators, use_spot, region, zone
        return 0.0  # bundled into the shape price

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # OCI internet egress: first 10 TB/month free, then ~$0.0085/GB.
        if num_gigabytes <= 10240:
            return 0.0
        return (num_gigabytes - 10240) * 0.0085

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        if resources.tpu_spec is not None:
            return [], fuzzy
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'oci', acc, count, resources.cpus, resources.memory,
                resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(name_filter=acc,
                                                      clouds=['oci'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('oci',
                                            resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('oci', cpus, memory)

    def validate_region_zone(self, region, zone):
        return catalog.validate_region_zone('oci', region, zone)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [z.name for z in (zones or [])],
            'use_spot': resources.use_spot,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'tpu': False,
            'instance_type': resources.instance_type,
            'num_nodes': 1,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if not os.path.exists(os.path.expanduser('~/.oci/config')):
            return False, ('OCI config not found. Run `oci setup config` '
                           '(and set oci.compartment_ocid in '
                           '~/.skytpu/config.yaml).')
        try:
            proc = subprocess.run(['oci', 'iam', 'region', 'list'],
                                  capture_output=True, text=True,
                                  timeout=15, check=False)
            if proc.returncode == 0:
                return True, None
            return False, ('`oci iam region list` failed: '
                           f'{proc.stderr.strip().splitlines()[:1]}')
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return False, 'oci CLI not installed or not responding.'

    def get_current_user_identity(self) -> Optional[List[str]]:
        path = os.path.expanduser('~/.oci/config')
        if not os.path.exists(path):
            return None
        with open(path, encoding='utf-8') as f:
            for line in f:
                if line.strip().startswith('user'):
                    _, _, value = line.partition('=')
                    return [value.strip()]
        return None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        if os.path.isdir(os.path.expanduser('~/.oci')):
            return {'~/.oci': '~/.oci'}
        return {}
