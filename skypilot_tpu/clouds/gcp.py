"""GCP: TPU slices (TPU-VM), GPU VMs, CPU VMs.

Parity: /root/reference/sky/clouds/gcp.py:190-934 (TPU-VM vs TPU-node
distinction, tpu template vars, pod-cannot-stop, spot-TPU cleanup) — rebuilt
around slices: there is no 'TPU-node' legacy mode and no `instance_type ==
'TPU-VM'` sentinel; a TPU request carries no instance type at all and deploys
through the queued-resources/TPU-VM API with an explicit capacity mode.
"""
from __future__ import annotations

import os
import subprocess
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu import config as config_lib
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import accelerator_registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

# Default TPU software version per generation (overridable via
# `accelerator_args: {runtime_version: ...}` or config tpu.runtime_version).
_DEFAULT_RUNTIME_VERSIONS = {
    'v2': 'tpu-ubuntu2204-base',
    'v3': 'tpu-ubuntu2204-base',
    'v4': 'tpu-ubuntu2204-base',
    'v5e': 'v2-alpha-tpuv5-lite',
    'v5p': 'v2-alpha-tpuv5',
    'v6e': 'v2-alpha-tpuv6e',
}

# GCP TPU API accelerator-type spelling per generation: the API still calls
# v5e 'v5litepod'.
_API_GENERATION_NAMES = {'v5e': 'v5litepod'}


def _validated_topology(topology: Optional[str],
                        spec: accelerator_registry.TpuSliceSpec) -> str:
    """Explicit topology must describe exactly the slice's chip count."""
    if not topology:
        return spec.topology_str
    try:
        dims = [int(d) for d in str(topology).lower().split('x')]
        chips = 1
        for d in dims:
            chips *= d
    except ValueError as e:
        raise ValueError(
            f'Bad TPU topology {topology!r}; expected NxN[xN].') from e
    if len(dims) < 2 or any(d <= 0 for d in dims):
        raise ValueError(
            f'Bad TPU topology {topology!r}; expected >= 2 positive '
            'dims like 4x4 or 2x2x4.')
    if chips != spec.num_chips:
        raise ValueError(
            f'topology {topology!r} is {chips} chips but '
            f'{spec.name} is a {spec.num_chips}-chip slice.')
    return str(topology)


def tpu_api_accelerator_type(spec: accelerator_registry.TpuSliceSpec) -> str:
    gen = _API_GENERATION_NAMES.get(spec.generation, spec.generation)
    return f'{gen}-{spec.size}'


class GCP(cloud_lib.Cloud):
    _REPR = 'GCP'
    PROVISIONER = 'gcp'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not supported on GCP TPU-VMs.',
    }

    @classmethod
    def check_features_are_supported(cls, resources, requested_features):
        super().check_features_are_supported(resources, requested_features)
        from skypilot_tpu import exceptions  # pylint: disable=import-outside-toplevel
        spec = resources.tpu_spec
        if spec is not None and spec.is_pod and (
                cloud_lib.CloudImplementationFeatures.STOP
                in requested_features):
            # Parity: reference gcp.py:190-201 — multi-host slices cannot be
            # stopped, only deleted.
            raise exceptions.NotSupportedError(
                f'Multi-host TPU slice {spec.name} cannot be stopped '
                '(GCP limitation); use down/terminate instead.')

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        spec = resources.tpu_spec
        if spec is not None:
            pairs = catalog.get_region_zones_for_tpu('gcp', spec.name,
                                                     resources.use_spot)
        elif resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'gcp', resources.instance_type, resources.use_spot)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, zone_name in pairs:
            if resources.region is not None and region_name != resources.region:
                continue
            if resources.zone is not None and zone_name != resources.zone:
                continue
            region = regions.setdefault(region_name,
                                        cloud_lib.Region(region_name))
            region.zones.append(cloud_lib.Zone(zone_name, region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('gcp', instance_type, use_spot, region,
                                       zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        acc, _ = next(iter(accelerators.items()))
        if accelerator_registry.is_tpu(acc):
            return catalog.get_tpu_hourly_cost('gcp', acc, use_spot, region,
                                               zone)
        # GPU prices are bundled into the hosting instance type's price.
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Public GCP internet egress tiering (reference optimizer.py:76-105
        # uses the same shape for its egress model).
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 1024:
            return num_gigabytes * 0.12
        return 1024 * 0.12 + (num_gigabytes - 1024) * 0.11

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        launchable: List['resources_lib.Resources'] = []
        spec = resources.tpu_spec
        if spec is not None:
            regions = self.regions_with_offering(resources)
            if regions:
                launchable.append(
                    resources.copy(cloud=self, instance_type=None))
            else:
                fuzzy.extend(
                    n for n in accelerator_registry.list_tpu_names(64)
                    if n.split('-')[1] == spec.generation)
            return launchable, fuzzy
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'gcp', acc, count, resources.cpus, resources.memory,
                resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(name_filter=acc,
                                                      clouds=['gcp'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('gcp', resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('gcp', cpus, memory)

    def validate_region_zone(self, region, zone):
        return catalog.validate_region_zone('gcp', region, zone)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name, region,
                                        zones) -> Dict[str, Any]:
        zone_names = [z.name for z in (zones or [])]
        spec = resources.tpu_spec
        common: Dict[str, Any] = {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': zone_names,
            'use_spot': resources.use_spot,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
        }
        if spec is not None:
            args = resources.accelerator_args or {}
            runtime_version = (
                args.get('runtime_version') or
                config_lib.get_nested(('tpu', 'runtime_version')) or
                _DEFAULT_RUNTIME_VERSIONS[spec.generation])
            provision_mode = resources.provision_mode.value
            common.update({
                'tpu': True,
                'tpu_generation': spec.generation,
                'tpu_accelerator_type': tpu_api_accelerator_type(spec),
                # An explicit accelerator_args topology (a non-default
                # ICI torus) overrides the registry default — but only
                # for the SAME chip count, or the TPU API rejects the
                # AcceleratorType/topology pair deep in provisioning.
                'tpu_topology': _validated_topology(
                    args.get('topology'), spec),
                'tpu_num_chips': spec.num_chips,
                'tpu_num_hosts': spec.num_hosts,
                'tpu_runtime_version': runtime_version,
                'provision_mode': provision_mode,
                'reservation': args.get('reservation'),
                'num_slices': resources.num_slices,
            })
        else:
            common.update({
                'tpu': False,
                'instance_type': resources.instance_type,
                'num_nodes': 1,
            })
        return common

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        adc = os.environ.get(
            'GOOGLE_APPLICATION_CREDENTIALS',
            os.path.expanduser(
                '~/.config/gcloud/application_default_credentials.json'))
        if os.path.exists(os.path.expanduser(adc)):
            return True, None
        try:
            proc = subprocess.run(
                ['gcloud', 'auth', 'list', '--format=value(account)'],
                capture_output=True, text=True, timeout=10, check=False)
            if proc.returncode == 0 and proc.stdout.strip():
                return True, None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return False, ('GCP credentials not found. Run `gcloud auth '
                       'application-default login` or set '
                       'GOOGLE_APPLICATION_CREDENTIALS.')

    def get_current_user_identity(self) -> Optional[List[str]]:
        try:
            proc = subprocess.run(
                ['gcloud', 'config', 'list', '--format=value(core.account)'],
                capture_output=True, text=True, timeout=10, check=False)
            account = proc.stdout.strip()
            return [account] if account else None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        mounts = {}
        gcloud_dir = os.path.expanduser('~/.config/gcloud')
        if os.path.isdir(gcloud_dir):
            mounts['~/.config/gcloud'] = '~/.config/gcloud'
        return mounts
