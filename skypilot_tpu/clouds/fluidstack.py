"""FluidStack: marketplace GPU VMs — a ninth fungible GPU pool.

Parity: /root/reference/sky/clouds/fluidstack.py:1-280 (feature
gates, `~/.fluidstack/api_key` credential check) — rebuilt on the
platform REST API behind an injectable transport
(provision/fluidstack/instance.py) instead of the reference's
fluidstack_utils requests wrapper.

FluidStack instances stop/start (the reference gated STOP for SDK
reasons; the platform API exposes it); no spot market, no custom
images, no per-instance firewall.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

CREDENTIALS_PATH = '~/.fluidstack/api_key'


def read_api_key() -> Optional[str]:
    key = os.environ.get('FLUIDSTACK_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        return f.read().strip() or None


class FluidStack(cloud_lib.Cloud):
    _REPR = 'FluidStack'
    PROVISIONER = 'fluidstack'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'FluidStack has no spot market.',
        cloud_lib.CloudImplementationFeatures.IMAGE_ID:
            'Instances boot the framework Ubuntu image.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Disk tier is fixed per configuration.',
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not implemented for FluidStack.',
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'No per-instance firewall API.',
        cloud_lib.CloudImplementationFeatures.HOST_CONTROLLERS:
            'Marketplace capacity is not suitable for long-lived '
            'controllers.',
    }

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        if resources.tpu_spec is not None or resources.use_spot:
            return []
        if resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'fluidstack', resources.instance_type, False)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, _ in pairs:
            if (resources.region is not None and
                    region_name != resources.region):
                continue
            regions.setdefault(region_name, cloud_lib.Region(region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('fluidstack', instance_type,
                                       use_spot, region, zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        del accelerators, use_spot, region, zone
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        del num_gigabytes
        return 0.0

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        if resources.tpu_spec is not None or resources.use_spot:
            return [], fuzzy
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'fluidstack', acc, count, resources.cpus,
                resources.memory, resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(
                    name_filter=acc, clouds=['fluidstack'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('fluidstack',
                                            resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('fluidstack', cpus,
                                                 memory)

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError(
                'FluidStack has no zone placement (region only); '
                f'got zone={zone!r}.')
        return catalog.validate_region_zone('fluidstack', region, None)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        del zones
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [],
            'use_spot': False,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': None,
            'tpu': False,
            'instance_type': resources.instance_type,
            'num_nodes': 1,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if read_api_key():
            return True, None
        return False, (f'FluidStack API key not found. Put the key in '
                       f'{CREDENTIALS_PATH} or set FLUIDSTACK_API_KEY.')

    def get_current_user_identity(self) -> Optional[List[str]]:
        key = read_api_key()
        return [f'fluidstack:{key[:8]}'] if key else None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        if os.path.exists(os.path.expanduser(CREDENTIALS_PATH)):
            return {CREDENTIALS_PATH: CREDENTIALS_PATH}
        return {}
