"""Cloud registry: name → Cloud singleton.

Parity: /root/reference/sky/clouds/cloud_registry.py (CLOUD_REGISTRY dict).
"""
from __future__ import annotations

from typing import Dict, Optional

from skypilot_tpu.clouds import aws
from skypilot_tpu.clouds import azure
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.clouds import cudo
from skypilot_tpu.clouds import docker
from skypilot_tpu.clouds import fluidstack
from skypilot_tpu.clouds import gcp
from skypilot_tpu.clouds import gke
from skypilot_tpu.clouds import ibm
from skypilot_tpu.clouds import kubernetes
from skypilot_tpu.clouds import lambda_cloud
from skypilot_tpu.clouds import local
from skypilot_tpu.clouds import oci
from skypilot_tpu.clouds import paperspace
from skypilot_tpu.clouds import runpod

CLOUD_REGISTRY: Dict[str, cloud_lib.Cloud] = {
    'aws': aws.AWS(),
    'azure': azure.Azure(),
    'cudo': cudo.Cudo(),
    'docker': docker.Docker(),
    'fluidstack': fluidstack.FluidStack(),
    'gcp': gcp.GCP(),
    'gke': gke.GKE(),
    'ibm': ibm.IBM(),
    'kubernetes': kubernetes.Kubernetes(),
    'lambda': lambda_cloud.LambdaCloud(),
    'local': local.Local(),
    'oci': oci.OCI(),
    'paperspace': paperspace.Paperspace(),
    'runpod': runpod.RunPod(),
}

# Aliases accepted by from_str (kept OUT of the registry dict so that
# `sky check` and registry iteration see each cloud exactly once).
_ALIASES = {'k8s': 'kubernetes', 'lambda_cloud': 'lambda'}


def from_str(name: Optional[str]) -> Optional[cloud_lib.Cloud]:
    if name is None:
        return None
    key = name.lower()
    cloud = CLOUD_REGISTRY.get(_ALIASES.get(key, key))
    if cloud is None:
        raise ValueError(
            f'Unknown cloud {name!r}. Available: {sorted(CLOUD_REGISTRY)}')
    return cloud
