"""Infra providers."""
from skypilot_tpu.clouds.cloud import Cloud
from skypilot_tpu.clouds.cloud import CloudImplementationFeatures
from skypilot_tpu.clouds.cloud import ProvisionMode
from skypilot_tpu.clouds.cloud import Region
from skypilot_tpu.clouds.cloud import Zone
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.gke import GKE
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.local import Local
from skypilot_tpu.clouds.registry import CLOUD_REGISTRY
from skypilot_tpu.clouds.registry import from_str

__all__ = [
    'Cloud', 'CloudImplementationFeatures', 'ProvisionMode', 'Region', 'Zone',
    'GCP', 'GKE', 'Kubernetes', 'Local', 'CLOUD_REGISTRY', 'from_str',
]
