"""AWS: GPU and CPU VMs — the fungible accelerator alternative to TPUs.

Parity: /root/reference/sky/clouds/aws.py:1-1084 (region enumeration,
pricing, deploy vars, credential checks) — minus what doesn't apply to
the TPU-first design: no TPUs live here, so every accelerator request
maps to a hosting EC2 instance type from the catalog; the optimizer
weighs these against GCP TPU slices with measured-MFU throughput priors
(utils/throughput_registry).
"""
from __future__ import annotations

import os
import subprocess
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class AWS(cloud_lib.Cloud):
    _REPR = 'AWS'
    PROVISIONER = 'aws'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not implemented for AWS.',
    }

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        if resources.tpu_spec is not None:
            return []  # TPUs are GCP-only.
        if resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'aws', resources.instance_type, resources.use_spot)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, zone_name in pairs:
            if (resources.region is not None and
                    region_name != resources.region):
                continue
            if resources.zone is not None and zone_name != resources.zone:
                continue
            region = regions.setdefault(region_name,
                                        cloud_lib.Region(region_name))
            region.zones.append(cloud_lib.Zone(zone_name, region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('aws', instance_type, use_spot,
                                       region, zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        # GPU prices are bundled into the hosting instance type's price.
        del accelerators, use_spot, region, zone
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Public AWS internet egress tiering.
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 10240:
            return num_gigabytes * 0.09
        return 10240 * 0.09 + (num_gigabytes - 10240) * 0.085

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        launchable: List['resources_lib.Resources'] = []
        if resources.tpu_spec is not None:
            return [], fuzzy  # TPUs do not exist on AWS.
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'aws', acc, count, resources.cpus, resources.memory,
                resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(name_filter=acc,
                                                      clouds=['aws'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('aws', resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('aws', cpus, memory)

    def validate_region_zone(self, region, zone):
        return catalog.validate_region_zone('aws', region, zone)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [z.name for z in (zones or [])],
            'use_spot': resources.use_spot,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'tpu': False,
            'instance_type': resources.instance_type,
            'num_nodes': 1,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if os.path.exists(os.path.expanduser('~/.aws/credentials')) or \
                os.environ.get('AWS_ACCESS_KEY_ID'):
            return True, None
        try:
            proc = subprocess.run(
                ['aws', 'sts', 'get-caller-identity'],
                capture_output=True, text=True, timeout=10, check=False)
            if proc.returncode == 0:
                return True, None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return False, ('AWS credentials not found. Run `aws configure` '
                       'or set AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY.')

    def get_current_user_identity(self) -> Optional[List[str]]:
        try:
            proc = subprocess.run(
                ['aws', 'sts', 'get-caller-identity',
                 '--query', 'Arn', '--output', 'text'],
                capture_output=True, text=True, timeout=10, check=False)
            arn = proc.stdout.strip()
            return [arn] if proc.returncode == 0 and arn else None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        aws_dir = os.path.expanduser('~/.aws')
        if os.path.isdir(aws_dir):
            return {'~/.aws': '~/.aws'}
        return {}
