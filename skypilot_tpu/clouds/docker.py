"""Docker cloud: local containers for image-faithful quick iteration.

Parity: /root/reference/sky/backends/local_docker_backend.py (a
parallel Backend class there; a cloud + provisioner here, so the whole
normal stack — optimizer, backend, skylet, jobs — runs unmodified
against containers).  Complements the `local` cloud: local emulates
slice hosts as bare directories (fastest, no daemon needed); docker
runs tasks inside the actual container image they would ship with.
"""
from __future__ import annotations

import subprocess
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class Docker(cloud_lib.Cloud):
    _REPR = 'Docker'
    PROVISIONER = 'docker'
    HAS_CATALOG = False

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.QUEUED_RESOURCE:
            'Container capacity is immediate.',
        cloud_lib.CloudImplementationFeatures.RESERVATION:
            'Container capacity is immediate.',
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'No disks to clone for containers.',
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'Containers are not preemptible capacity.',
    }

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        del resources
        return [
            cloud_lib.Region('docker').set_zones(
                [cloud_lib.Zone('docker', 'docker')])
        ]

    def instance_type_to_hourly_cost(self, instance_type, use_spot,
                                     region, zone) -> float:
        return 0.0

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        return 0.0

    def get_feasible_launchable_resources(self, resources):
        if resources.tpu_spec is not None or resources.accelerators:
            # Plain CPU containers: no TPUs, and no GPU passthrough —
            # accepting an accelerator request at $0 would win every
            # cost comparison and land the job on a GPU-less container.
            return [], []
        return [resources.copy(cloud=self, instance_type='docker')], []

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        del cpus, memory
        return 'docker'

    def validate_region_zone(self, region, zone):
        if region not in (None, 'docker') or zone not in (None, 'docker'):
            raise ValueError('The docker cloud has a single region/zone '
                             "named 'docker'.")
        return region, zone

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [z.name for z in (zones or [])],
            'tpu': False,
            'image_id': resources.image_id,
            'instance_type': resources.instance_type or 'docker',
            'use_spot': False,
        }

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        try:
            proc = subprocess.run(['docker', 'info'], capture_output=True,
                                  timeout=10, check=False)
            if proc.returncode == 0:
                return True, None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return False, ('docker daemon not reachable; install docker or '
                       'start the daemon.')

    def get_current_user_identity(self) -> Optional[List[str]]:
        from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
        return [common_utils.get_user_hash()]
