"""Cloud abstraction: capability flags, pricing, feasibility, deploy vars.

Parity: /root/reference/sky/clouds/cloud.py:28-820 (`Cloud` ABC,
`CloudImplementationFeatures`, region/zone iteration, pricing hooks,
`make_deploy_resources_variables`, feasibility, credential checks).
TPU-first reshaping: feasibility returns *slice launchables* (a TPU slice or
a GPU/CPU VM group) and deploy variables describe a slice request (generation,
topology, hosts, capacity type incl. QUEUED) instead of a Ray autoscaler
node config.
"""
from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class CloudImplementationFeatures(enum.Enum):
    """Capabilities a task may require; clouds declare what they cannot do.

    Parity: reference cloud.py:28-48, extended with TPU capacity modes.
    """
    STOP = 'stop'
    MULTI_NODE = 'multi_node'
    IMAGE_ID = 'image_id'
    DOCKER_IMAGE = 'docker_image'
    SPOT_INSTANCE = 'spot_instance'
    QUEUED_RESOURCE = 'queued_resource'    # async TPU capacity (new)
    RESERVATION = 'reservation'
    CUSTOM_DISK_TIER = 'custom_disk_tier'
    OPEN_PORTS = 'open_ports'
    STORAGE_MOUNTING = 'storage_mounting'
    HOST_CONTROLLERS = 'host_controllers'
    AUTOSTOP = 'autostop'
    TPU = 'tpu'
    CLONE_DISK = 'clone_disk'


class ProvisionMode(enum.Enum):
    """How TPU capacity is requested (`resources.capacity` in task YAML)."""
    ON_DEMAND = 'on_demand'
    SPOT = 'spot'
    QUEUED = 'queued'        # GCP queued-resources: async, may WAIT
    RESERVED = 'reserved'


@dataclasses.dataclass
class Region:
    name: str
    zones: List['Zone'] = dataclasses.field(default_factory=list)

    def set_zones(self, zones: List['Zone']) -> 'Region':
        self.zones = zones
        return self


@dataclasses.dataclass
class Zone:
    name: str
    region: Optional[str] = None


class Cloud:
    """Base class for infra providers (GCP TPU/GPU, GKE, Local)."""

    # Subclasses override.
    _REPR = 'Cloud'
    # Clouds without a price catalog (local/docker: free local capacity)
    # skip instance-type catalog validation.
    HAS_CATALOG = True
    # Which provision module implements this cloud
    # (skypilot_tpu.provision.<name>).
    PROVISIONER = 'none'

    _CLOUD_UNSUPPORTED_FEATURES: Dict[CloudImplementationFeatures, str] = {}

    def __repr__(self) -> str:
        return self._REPR

    @property
    def name(self) -> str:
        return self._REPR.lower()

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Cloud) and self._REPR == other._REPR

    def __hash__(self) -> int:
        return hash(self._REPR)

    # --------------------------------------------------------- capabilities

    @classmethod
    def check_features_are_supported(
            cls, resources: 'resources_lib.Resources',
            requested_features: Set[CloudImplementationFeatures]) -> None:
        """Raise NotSupportedError if any requested feature is unsupported."""
        del resources
        from skypilot_tpu import exceptions  # pylint: disable=import-outside-toplevel
        unsupported = {
            f: reason for f, reason in cls._CLOUD_UNSUPPORTED_FEATURES.items()
            if f in requested_features
        }
        if unsupported:
            raise exceptions.NotSupportedError(
                f'{cls._REPR} does not support: '
                f'{ {f.value: r for f, r in unsupported.items()} }')

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources: 'resources_lib.Resources'
                              ) -> List[Region]:
        raise NotImplementedError

    def zones_provision_loop(
            self, resources: 'resources_lib.Resources',
            region: Optional[str] = None
    ) -> Iterator[Tuple[Region, Optional[List[Zone]]]]:
        """Yield (region, zones) tuples in provisioning-attempt order.

        Mirrors the reference's `_yield_zones` contract
        (cloud_vm_ray_backend.py:1178): the failover loop walks this.
        """
        for r in self.regions_with_offering(resources):
            if region is not None and r.name != region:
                continue
            yield r, r.zones or None

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type: str, use_spot: bool,
                                     region: Optional[str],
                                     zone: Optional[str]) -> float:
        raise NotImplementedError

    def accelerators_to_hourly_cost(self, accelerators: Dict[str, int],
                                    use_spot: bool, region: Optional[str],
                                    zone: Optional[str]) -> float:
        """Extra cost of accelerators (0 when bundled into instance price)."""
        raise NotImplementedError

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        """Concretize a (possibly partial) request into launchable resources.

        Returns (launchables, fuzzy_candidate_names). Parity:
        reference cloud.py:369 + optimizer.py:1255.
        """
        raise NotImplementedError

    def get_default_instance_type(self, cpus: Optional[str],
                                  memory: Optional[str]) -> Optional[str]:
        raise NotImplementedError

    def validate_region_zone(self, region: Optional[str], zone: Optional[str]
                             ) -> Tuple[Optional[str], Optional[str]]:
        raise NotImplementedError

    # ------------------------------------------------------------ deploy

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: Region, zones: Optional[List[Zone]]) -> Dict[str, Any]:
        """Resources → variables consumed by this cloud's provisioner."""
        raise NotImplementedError

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        """(ok, reason-if-not)."""
        raise NotImplementedError

    def get_current_user_identity(self) -> Optional[List[str]]:
        return None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        """Local credential files to replicate onto provisioned hosts."""
        return {}
