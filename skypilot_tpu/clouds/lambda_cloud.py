"""Lambda Cloud: bare-metal GPU boxes — a fourth fungible GPU pool.

Parity: /root/reference/sky/clouds/lambda_cloud.py:1-301 (region
enumeration, pricing, feature gates, `~/.lambda_cloud/lambda_keys`
credential check) — rebuilt on the public REST API behind an
injectable transport (provision/lambda_cloud/instance.py) instead of
the reference's `lambda_utils` requests wrapper.

Lambda's model is simpler than the hyperscalers and the feature gates
say so honestly: no stop/resume (instances only launch and terminate),
no spot market, no custom images, no per-instance port rules (boxes
come up with an open firewall profile), region-level placement only.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

CREDENTIALS_PATH = '~/.lambda_cloud/lambda_keys'


def read_api_key() -> Optional[str]:
    """API key from env or the reference-compatible keys file
    (`api_key = <key>` lines)."""
    key = os.environ.get('LAMBDA_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        for line in f:
            if line.strip().startswith('api_key'):
                _, _, value = line.partition('=')
                return value.strip() or None
    return None


class LambdaCloud(cloud_lib.Cloud):
    _REPR = 'Lambda'
    PROVISIONER = 'lambda_cloud'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.STOP:
            'Lambda instances cannot be stopped (launch/terminate only).',
        cloud_lib.CloudImplementationFeatures.AUTOSTOP:
            'No stop support; use autodown.',
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'Lambda has no spot market.',
        cloud_lib.CloudImplementationFeatures.IMAGE_ID:
            'Lambda boxes boot a fixed Ubuntu + CUDA image.',
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'No per-instance firewall API; ports are account-level.',
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not implemented for Lambda.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Disk size/tier is fixed per instance type.',
    }

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        if resources.tpu_spec is not None:
            return []  # TPUs are GCP-only.
        if resources.use_spot:
            return []
        if resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'lambda', resources.instance_type, False)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, _ in pairs:  # no zones on Lambda
            if (resources.region is not None and
                    region_name != resources.region):
                continue
            regions.setdefault(region_name, cloud_lib.Region(region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('lambda', instance_type, use_spot,
                                       region, zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        del accelerators, use_spot, region, zone
        return 0.0  # bundled into the instance price

    def get_egress_cost(self, num_gigabytes: float) -> float:
        del num_gigabytes
        return 0.0  # Lambda meters no egress

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        if resources.tpu_spec is not None:
            return [], fuzzy
        if resources.use_spot:
            return [], fuzzy
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'lambda', acc, count, resources.cpus, resources.memory,
                resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(name_filter=acc,
                                                      clouds=['lambda'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('lambda',
                                            resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('lambda', cpus, memory)

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError(
                'Lambda has no zone placement (region only); '
                f'got zone={zone!r}.')
        return catalog.validate_region_zone('lambda', region, None)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        del zones
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [],
            'use_spot': False,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': None,
            'tpu': False,
            'instance_type': resources.instance_type,
            'num_nodes': 1,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if read_api_key():
            return True, None
        return False, (f'Lambda API key not found. Put `api_key = ...` '
                       f'in {CREDENTIALS_PATH} or set LAMBDA_API_KEY.')

    def get_current_user_identity(self) -> Optional[List[str]]:
        key = read_api_key()
        # The API exposes no identity endpoint; the key prefix is the
        # stable account discriminator.
        return [f'lambda:{key[:8]}'] if key else None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        if os.path.exists(os.path.expanduser(CREDENTIALS_PATH)):
            return {CREDENTIALS_PATH: CREDENTIALS_PATH}
        return {}
