"""Azure: GPU and CPU VMs — the third fungible accelerator pool.

Parity: /root/reference/sky/clouds/azure.py:1-689 (region enumeration,
pricing, deploy vars, credential checks via `az account show`) — minus
what doesn't apply to the TPU-first design: no TPUs live here, so every
accelerator request maps to a hosting VM size from the catalog, and the
optimizer weighs those against GCP TPU slices (and AWS GPUs) with
measured-MFU throughput priors (utils/throughput_registry).

Azure has no availability-zone placement in this flow (the reference
provisions region-level too, sky/clouds/azure.py:378-380): catalog rows
carry an empty zone and the provisioner ignores zones.
"""
from __future__ import annotations

import os
import subprocess
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class Azure(cloud_lib.Cloud):
    _REPR = 'Azure'
    PROVISIONER = 'azure'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not implemented for Azure.',
    }

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        if resources.tpu_spec is not None:
            return []  # TPUs are GCP-only.
        if resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'azure', resources.instance_type, resources.use_spot)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, _ in pairs:  # zone column is empty on Azure
            if (resources.region is not None and
                    region_name != resources.region):
                continue
            regions.setdefault(region_name, cloud_lib.Region(region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('azure', instance_type, use_spot,
                                       region, zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        # GPU prices are bundled into the hosting VM size's price.
        del accelerators, use_spot, region, zone
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Azure internet egress: first 100 GB/month free, then a flat
        # tier (reference sky/clouds/azure.py:120-139 shape).
        if num_gigabytes <= 100:
            return 0.0
        return (num_gigabytes - 100) * 0.0875

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        if resources.tpu_spec is not None:
            return [], fuzzy  # TPUs do not exist on Azure.
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'azure', acc, count, resources.cpus, resources.memory,
                resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(name_filter=acc,
                                                      clouds=['azure'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('azure',
                                            resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('azure', cpus, memory)

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError(
                'Azure does not take zone placement here (region only); '
                f'got zone={zone!r}.')
        return catalog.validate_region_zone('azure', region, None)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        del zones  # region-level provisioning
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [],
            'use_spot': resources.use_spot,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'tpu': False,
            'instance_type': resources.instance_type,
            'num_nodes': 1,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        try:
            proc = subprocess.run(['az', 'account', 'show'],
                                  capture_output=True, text=True,
                                  timeout=15, check=False)
            if proc.returncode == 0:
                return True, None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return False, ('Azure credentials not found. Run `az login` '
                       '(and `az account set -s <subscription>`).')

    def get_current_user_identity(self) -> Optional[List[str]]:
        try:
            proc = subprocess.run(
                ['az', 'account', 'show',
                 '--query', '[user.name,id]', '--output', 'tsv'],
                capture_output=True, text=True, timeout=15, check=False)
            lines = proc.stdout.split()
            return lines or None if proc.returncode == 0 else None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        azure_dir = os.path.expanduser('~/.azure')
        if os.path.isdir(azure_dir):
            return {'~/.azure': '~/.azure'}
        return {}
