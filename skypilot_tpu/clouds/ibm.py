"""IBM Cloud VPC: GPU VSIs — a tenth fungible GPU pool.

Parity: /root/reference/sky/clouds/ibm.py:1-495 (feature gates, region
enumeration, `~/.ibm/credentials.yaml` check) — rebuilt on the
`ibmcloud is` CLI's JSON output with an injectable runner
(provision/ibm/instance.py), the same no-SDK seam as aws/azure/oci,
instead of the reference's ibm-vpc SDK + Ray node provider.

Placement is region + zone (VPC zones like 'us-south-1').  The VPC
and subnet the framework may use come from the layered config
(`ibm.vpc_id`, `ibm.subnet_id`) — IBM VPC networking is account
topology, not something a provisioner should invent.  GPU profiles
(gx2 V100, gx3 L4/L40S, gx3d H100) price via the catalog.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

CREDENTIALS_PATH = '~/.ibm/credentials.yaml'


def read_credentials() -> Dict[str, str]:
    """`iam_api_key:`/`resource_group_id:` from the reference-
    compatible credentials.yaml (flat YAML subset, no dependency)."""
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return {}
    creds: Dict[str, str] = {}
    with open(path, encoding='utf-8') as f:
        for line in f:
            key, sep, value = line.strip().partition(':')
            if sep and value.strip():
                creds[key.strip()] = value.strip().strip('"\'')
    return creds


class IBM(cloud_lib.Cloud):
    _REPR = 'IBM'
    PROVISIONER = 'ibm'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'IBM VPC has no spot market for VSIs.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Boot volume tier is fixed per profile.',
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not implemented for IBM.',
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'Ports ride the VPC security group, not a per-instance '
            'API; configure the group instead.',
    }

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        if resources.tpu_spec is not None or resources.use_spot:
            return []
        if resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'ibm', resources.instance_type, False)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, zone_name in pairs:
            if (resources.region is not None and
                    region_name != resources.region):
                continue
            if resources.zone is not None and zone_name != resources.zone:
                continue
            region = regions.setdefault(region_name,
                                        cloud_lib.Region(region_name))
            region.zones.append(cloud_lib.Zone(zone_name, region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('ibm', instance_type, use_spot,
                                       region, zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        del accelerators, use_spot, region, zone
        return 0.0

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # IBM internet egress: first 250 GB/month free, then a flat
        # tier (reference sky/clouds/ibm.py shape).
        if num_gigabytes <= 250:
            return 0.0
        return (num_gigabytes - 250) * 0.09

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        if resources.tpu_spec is not None or resources.use_spot:
            return [], fuzzy
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'ibm', acc, count, resources.cpus, resources.memory,
                resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(name_filter=acc,
                                                      clouds=['ibm'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('ibm',
                                            resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('ibm', cpus, memory)

    def validate_region_zone(self, region, zone):
        return catalog.validate_region_zone('ibm', region, zone)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [z.name for z in (zones or [])],
            'use_spot': False,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': resources.image_id,
            'tpu': False,
            'instance_type': resources.instance_type,
            'num_nodes': 1,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        creds = read_credentials()
        missing = {'iam_api_key', 'resource_group_id'} - set(creds)
        if not missing:
            return True, None
        return False, (f'IBM credentials incomplete: missing '
                       f'{sorted(missing)} in {CREDENTIALS_PATH} '
                       '(and set ibm.vpc_id / ibm.subnet_id in '
                       '~/.skytpu/config.yaml; `ibmcloud login '
                       '--apikey` authenticates the CLI).')

    def get_current_user_identity(self) -> Optional[List[str]]:
        creds = read_credentials()
        key = creds.get('iam_api_key')
        return [f'ibm:{key[:8]}'] if key else None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        if os.path.exists(os.path.expanduser(CREDENTIALS_PATH)):
            return {CREDENTIALS_PATH: CREDENTIALS_PATH}
        return {}
