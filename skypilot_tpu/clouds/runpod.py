"""RunPod: community GPU pods — a sixth fungible GPU pool.

Parity: /root/reference/sky/clouds/runpod.py:1-280 (feature gates,
region enumeration, `~/.runpod/config.toml` credential check) —
rebuilt on RunPod's GraphQL API behind an injectable transport
(provision/runpod/instance.py) instead of the reference's `runpod`
SDK.

RunPod is single-node GPU pods: no gang interconnect, no spot market
via the API, no stop/resume worth relying on for training state (the
container filesystem survives a stop but the GPU is released and may
not come back) — the feature gates mirror the reference's honest
list, so the optimizer only routes single-node, on-demand,
COPY-storage tasks here.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

CREDENTIALS_PATH = '~/.runpod/config.toml'


def read_api_key() -> Optional[str]:
    """API key from env or the reference-compatible config.toml
    (`api_key = "<key>"` under any section)."""
    key = os.environ.get('RUNPOD_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        for line in f:
            stripped = line.strip()
            if stripped.startswith('api_key'):
                _, _, value = stripped.partition('=')
                return value.strip().strip('"\'') or None
    return None


class RunPod(cloud_lib.Cloud):
    _REPR = 'RunPod'
    PROVISIONER = 'runpod'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.STOP:
            'Stopping pods releases the GPU; not supported.',
        cloud_lib.CloudImplementationFeatures.AUTOSTOP:
            'No stop support; use autodown.',
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'The RunPod API exposes no spot market.',
        cloud_lib.CloudImplementationFeatures.MULTI_NODE:
            'No gang interconnect between pods.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Disk tier is fixed per pod type.',
        cloud_lib.CloudImplementationFeatures.STORAGE_MOUNTING:
            'Object-store mounting is unavailable in pods; use '
            'mode: COPY.',
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'Disk cloning is not implemented for RunPod.',
        cloud_lib.CloudImplementationFeatures.IMAGE_ID:
            'Pods boot the framework CUDA image.',
        # OPEN_PORTS is supported: declared ports are opened AT POD
        # CREATION (the only time RunPod allows it), which is exactly
        # when this framework opens ports (ProvisionConfig.
        # ports_to_open) — so port-declaring tasks are launchable.
    }

    # ------------------------------------------------------- regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        if resources.tpu_spec is not None or resources.use_spot:
            return []
        if resources.instance_type is not None:
            pairs = catalog.get_region_zones_for_instance_type(
                'runpod', resources.instance_type, False)
        else:
            pairs = []
        regions: Dict[str, cloud_lib.Region] = {}
        for region_name, _ in pairs:  # no zones on RunPod
            if (resources.region is not None and
                    region_name != resources.region):
                continue
            regions.setdefault(region_name, cloud_lib.Region(region_name))
        return list(regions.values())

    # ------------------------------------------------------------- pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return catalog.get_hourly_cost('runpod', instance_type, use_spot,
                                       region, zone)

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        del accelerators, use_spot, region, zone
        return 0.0  # bundled into the pod price

    def get_egress_cost(self, num_gigabytes: float) -> float:
        del num_gigabytes
        return 0.0  # RunPod meters no egress

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        fuzzy: List[str] = []
        if resources.tpu_spec is not None or resources.use_spot:
            return [], fuzzy
        if resources.accelerators:
            acc, count = next(iter(resources.accelerators.items()))
            instance_types = catalog.get_instance_type_for_accelerator(
                'runpod', acc, count, resources.cpus, resources.memory,
                resources.region, resources.zone)
            if not instance_types:
                offerings = catalog.list_accelerators(name_filter=acc,
                                                      clouds=['runpod'])
                fuzzy.extend(sorted(offerings))
                return [], fuzzy
            return [
                resources.copy(cloud=self, instance_type=instance_types[0])
            ], fuzzy
        if resources.instance_type is not None:
            if catalog.instance_type_exists('runpod',
                                            resources.instance_type):
                return [resources.copy(cloud=self)], fuzzy
            return [], fuzzy
        default = self.get_default_instance_type(resources.cpus,
                                                 resources.memory)
        if default is None:
            return [], fuzzy
        return [resources.copy(cloud=self, instance_type=default)], fuzzy

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return catalog.get_default_instance_type('runpod', cpus, memory)

    def validate_region_zone(self, region, zone):
        if zone is not None:
            raise ValueError(
                'RunPod has no zone placement (region only); '
                f'got zone={zone!r}.')
        return catalog.validate_region_zone('runpod', region, None)

    # ------------------------------------------------------------- deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        del zones
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [],
            'use_spot': False,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or []),
            'disk_size': resources.disk_size,
            'image_id': None,
            'tpu': False,
            'instance_type': resources.instance_type,
            'num_nodes': 1,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        if read_api_key():
            return True, None
        return False, (f'RunPod API key not found. Put `api_key = "..."` '
                       f'in {CREDENTIALS_PATH} or set RUNPOD_API_KEY.')

    def get_current_user_identity(self) -> Optional[List[str]]:
        key = read_api_key()
        return [f'runpod:{key[:8]}'] if key else None

    def get_credential_file_mounts(self) -> Dict[str, str]:
        if os.path.exists(os.path.expanduser(CREDENTIALS_PATH)):
            return {CREDENTIALS_PATH: CREDENTIALS_PATH}
        return {}
