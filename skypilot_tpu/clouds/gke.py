"""GKE: TPU slices as node pools in a Google Kubernetes Engine cluster.

The reference's Kubernetes path has **no TPU support**
(/root/reference/sky/provision/kubernetes/utils.py:517 TODO); here GKE
TPU node pools are a first-class second provisioner (SURVEY.md §7.8).
Pricing/regions reuse the GCP TPU catalog (node pools bill as the
underlying TPU VMs).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import config as config_lib
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.clouds import gcp

# GKE TPU machine types per generation x chips-per-host
# (cloud.google.com/kubernetes-engine/docs/concepts/tpus).
_MACHINE_TYPES = {
    ('v4', 4): 'ct4p-hightpu-4t',
    ('v5p', 4): 'ct5p-hightpu-4t',
    ('v5e', 1): 'ct5lp-hightpu-1t',
    ('v5e', 4): 'ct5lp-hightpu-4t',
    ('v5e', 8): 'ct5lp-hightpu-8t',
    ('v5litepod', 1): 'ct5lp-hightpu-1t',
    ('v5litepod', 4): 'ct5lp-hightpu-4t',
    ('v5litepod', 8): 'ct5lp-hightpu-8t',
    ('v6e', 1): 'ct6e-standard-1t',
    ('v6e', 4): 'ct6e-standard-4t',
    ('v6e', 8): 'ct6e-standard-8t',
}


class GKE(gcp.GCP):
    _REPR = 'GKE'
    PROVISIONER = 'gke'

    _CLOUD_UNSUPPORTED_FEATURES = {
        **gcp.GCP._CLOUD_UNSUPPORTED_FEATURES,  # pylint: disable=protected-access
        cloud_lib.CloudImplementationFeatures.STOP:
            'GKE node pools are deleted, not stopped.',
    }

    def get_feasible_launchable_resources(self, resources):
        # TPU-only: GKE CPU/GPU workloads go through the k8s ecosystem
        # proper; this cloud exists to gang-schedule TPU slices.
        spec = resources.tpu_spec
        if spec is None:
            return [], []
        chips_per_host = max(1, spec.num_chips // max(1, spec.num_hosts))
        if (spec.generation, chips_per_host) not in _MACHINE_TYPES:
            # No node-pool machine type (e.g. v2/v3): reject at optimize
            # time so the search falls back to GCP TPU-VMs instead of
            # failing deep in provisioning.
            fuzzy = sorted({f'tpu-{gen}'
                            for gen, _ in _MACHINE_TYPES})
            return [], fuzzy
        return super().get_feasible_launchable_resources(resources)

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        common = super().make_deploy_resources_variables(
            resources, cluster_name, region, zones)
        spec = resources.tpu_spec
        assert spec is not None
        chips_per_host = max(1, spec.num_chips // max(1, spec.num_hosts))
        machine_type = _MACHINE_TYPES.get(
            (spec.generation, chips_per_host))
        common.update({
            'gke_cluster': config_lib.get_nested(('gke', 'cluster'), None),
            'gke_location': config_lib.get_nested(('gke', 'location'),
                                                  region.name),
            'gke_machine_type': machine_type,
            'gke_namespace': config_lib.get_nested(('gke', 'namespace'),
                                                   'default'),
            'gke_context': config_lib.get_nested(('gke', 'context'), None),
        })
        return common

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        ok, hint = super().check_credentials()
        if not ok:
            return ok, hint
        if config_lib.get_nested(('gke', 'cluster'), None) is None:
            return False, ('Set gke.cluster (and gke.location) in '
                           '~/.skytpu/config.yaml to name the GKE '
                           'cluster that hosts TPU node pools.')
        return True, None
