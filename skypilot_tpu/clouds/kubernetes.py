"""Generic Kubernetes cloud: pods as cluster hosts (CPU / GPU).

Parity: /root/reference/sky/clouds/kubernetes.py (642 LoC; pods stand in
for VMs, `{cpus}CPU--{mem}GB` virtual instance types, nvidia.com/gpu
requests) + /root/reference/sky/provision/kubernetes/.  Differences,
TPU-first: TPU slices on Kubernetes go through the GKE cloud (node
pools + google.com/tpu — the reference's k8s path has NO TPU support,
utils.py:517 TODO); this cloud covers the complementary CPU/GPU pods on
*any* kubeconfig context (kind, on-prem, EKS, ...).

Virtual instance types are `k8s-<cpus>cpu-<mem>gb` — pods have no
catalog; price is 0 (pre-owned capacity), matching the reference's
treatment of k8s as free capacity that always wins cost ties when
feasible.
"""
from __future__ import annotations

import re
import subprocess
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import config as config_lib
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

_INSTANCE_RE = re.compile(r'^k8s-(\d+)cpu-(\d+)gb$')
_DEFAULT_CPUS = 2
_DEFAULT_MEM = 8

# GPU resource key per vendor; node-selector handled via config
# (`kubernetes.gpu_label`).  nvidia.com/gpu covers the common case.
_GPU_RESOURCE_KEY = 'nvidia.com/gpu'


def make_instance_type(cpus: int, mem_gb: int) -> str:
    return f'k8s-{cpus}cpu-{mem_gb}gb'


def parse_instance_type(instance_type: str) -> Optional[Tuple[int, int]]:
    m = _INSTANCE_RE.match(instance_type or '')
    if m is None:
        return None
    return int(m.group(1)), int(m.group(2))


def _parse_plus(value: Optional[str], default: int) -> int:
    """'4', '4+', 4.0 → 4; None → default."""
    if value is None:
        return default
    s = str(value).strip().rstrip('+')
    try:
        return max(1, int(float(s)))
    except ValueError:
        return default


class Kubernetes(cloud_lib.Cloud):
    _REPR = 'Kubernetes'
    PROVISIONER = 'kubernetes'
    HAS_CATALOG = False

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.STOP:
            'Pods are deleted, not stopped.',
        cloud_lib.CloudImplementationFeatures.AUTOSTOP:
            'Pods are deleted, not stopped.',
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'Pods are not preemptible capacity.',
        cloud_lib.CloudImplementationFeatures.QUEUED_RESOURCE:
            'Pod capacity is immediate.',
        cloud_lib.CloudImplementationFeatures.RESERVATION:
            'No reservations for pods.',
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'No disks to clone for pods.',
        cloud_lib.CloudImplementationFeatures.TPU:
            'TPU-on-Kubernetes goes through the GKE cloud '
            '(node pools + google.com/tpu).',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Pod ephemeral storage has no disk tiers.',
    }

    # ------------------------------------------------------ regions/zones

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        del resources
        context = config_lib.get_nested(('kubernetes', 'context'),
                                        None) or 'in-context'
        return [
            cloud_lib.Region(context).set_zones(
                [cloud_lib.Zone(context, context)])
        ]

    def validate_region_zone(self, region, zone):
        # Region == kubeconfig context; any single name is accepted.
        return region, zone

    # ------------------------------------------------------------ pricing

    def instance_type_to_hourly_cost(self, instance_type, use_spot,
                                     region, zone) -> float:
        del instance_type, use_spot, region, zone
        return 0.0

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        del accelerators, use_spot, region, zone
        return 0.0

    # -------------------------------------------------------- feasibility

    def get_feasible_launchable_resources(self, resources):
        if resources.tpu_spec is not None:
            # TPU slices ride the GKE cloud.
            return [], []
        if resources.use_spot:
            return [], []
        if resources.instance_type:
            if parse_instance_type(resources.instance_type) is None:
                return [], [make_instance_type(_DEFAULT_CPUS, _DEFAULT_MEM)]
            return [resources.copy(cloud=self)], []
        cpus = _parse_plus(resources.cpus, _DEFAULT_CPUS)
        mem = _parse_plus(resources.memory, _DEFAULT_MEM)
        return [resources.copy(cloud=self,
                               instance_type=make_instance_type(cpus, mem))
                ], []

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        return make_instance_type(_parse_plus(cpus, _DEFAULT_CPUS),
                                  _parse_plus(memory, _DEFAULT_MEM))

    # ------------------------------------------------------------ deploy

    def make_deploy_resources_variables(self, resources, cluster_name,
                                        region, zones) -> Dict[str, Any]:
        parsed = parse_instance_type(
            resources.instance_type or
            make_instance_type(_DEFAULT_CPUS, _DEFAULT_MEM))
        cpus, mem = parsed or (_DEFAULT_CPUS, _DEFAULT_MEM)
        gpus = 0
        gpu_type = None
        accels = resources.accelerators
        if accels:
            gpu_type, gpus = next(iter(accels.items()))
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [z.name for z in (zones or [])],
            'tpu': False,
            'instance_type': resources.instance_type,
            'cpus': cpus,
            'memory_gb': mem,
            'gpus': int(gpus),
            'gpu_type': gpu_type,
            'gpu_resource_key': config_lib.get_nested(
                ('kubernetes', 'gpu_resource_key'), _GPU_RESOURCE_KEY),
            'gpu_label': config_lib.get_nested(
                ('kubernetes', 'gpu_label'), None),
            'image_id': resources.image_id or config_lib.get_nested(
                ('kubernetes', 'image'), None),
            'namespace': config_lib.get_nested(
                ('kubernetes', 'namespace'), 'default'),
            'context': config_lib.get_nested(
                ('kubernetes', 'context'), None),
            'use_spot': False,
        }

    # --------------------------------------------------------- credentials

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        # Probe the SAME context provisioning will use: a configured
        # `kubernetes.context` must be pinned here too, or the check
        # reflects whatever ambient current-context happens to be.
        argv = ['kubectl']
        context = config_lib.get_nested(('kubernetes', 'context'), None)
        if context:
            argv += ['--context', context]
        argv += ['cluster-info', '--request-timeout=5s']
        try:
            proc = subprocess.run(argv, capture_output=True, timeout=15,
                                  check=False)
            if proc.returncode == 0:
                return True, None
            return False, ('kubectl cannot reach a cluster: '
                           f'{(proc.stderr or b"").decode()[-200:]}')
        except (FileNotFoundError, subprocess.TimeoutExpired) as e:
            return False, f'kubectl unavailable: {e}'

    def get_current_user_identity(self) -> Optional[List[str]]:
        try:
            proc = subprocess.run(
                ['kubectl', 'config', 'current-context'],
                capture_output=True, text=True, timeout=10, check=False)
            if proc.returncode == 0 and proc.stdout.strip():
                return [proc.stdout.strip()]
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return None
