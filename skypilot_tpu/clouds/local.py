"""Local cloud: hermetic slice emulation for tests and quick iteration.

The reference has no fake provisioner — anything touching provisioning is
only covered by real-cloud smoke tests (SURVEY.md §4 calls this out as the
thing to improve). The Local cloud fills that hole: every "host" of a slice
is a local directory + subprocess, so gang scheduling, log multiplexing,
failure fan-in, autostop, and recovery logic are testable without any cloud.
It doubles as the reference's `LocalDockerBackend` replacement for quick
iteration (/root/reference/sky/backends/local_docker_backend.py:1-409).

A TPU request (e.g. `tpu-v5e-16`) is honored shape-wise: the slice spec's
`num_hosts` local host processes are created, each exporting the TPU job
contract env, so multi-host ranks behave as they would on a real slice.
"""
from __future__ import annotations

import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class Local(cloud_lib.Cloud):
    _REPR = 'Local'
    PROVISIONER = 'local'

    _CLOUD_UNSUPPORTED_FEATURES = {
        cloud_lib.CloudImplementationFeatures.IMAGE_ID:
            'Local hosts run on the client machine; no images.',
        cloud_lib.CloudImplementationFeatures.QUEUED_RESOURCE:
            'Local capacity is immediate.',
        cloud_lib.CloudImplementationFeatures.RESERVATION:
            'Local capacity is immediate.',
        cloud_lib.CloudImplementationFeatures.CLONE_DISK:
            'No disks to clone locally.',
    }

    def regions_with_offering(self, resources) -> List[cloud_lib.Region]:
        del resources
        return [
            cloud_lib.Region('local').set_zones(
                [cloud_lib.Zone('local', 'local')])
        ]

    def instance_type_to_hourly_cost(self, instance_type, use_spot, region,
                                     zone) -> float:
        return 0.0

    def accelerators_to_hourly_cost(self, accelerators, use_spot, region,
                                    zone) -> float:
        return 0.0

    def get_feasible_launchable_resources(self, resources):
        # Local accepts any shape: accelerators are emulated (host-count
        # honored, no real chips), so everything is feasible at zero cost.
        # TPU requests stay instance-type-less (the slice is the unit).
        if resources.tpu_spec is not None:
            return [resources.copy(cloud=self, instance_type=None)], []
        return [resources.copy(cloud=self, instance_type='local')], []

    def get_default_instance_type(self, cpus, memory) -> Optional[str]:
        del cpus, memory
        return 'local'

    def validate_region_zone(self, region, zone):
        return catalog.validate_region_zone('local', region, zone)

    def make_deploy_resources_variables(self, resources, cluster_name, region,
                                        zones) -> Dict[str, Any]:
        spec = resources.tpu_spec
        num_hosts = spec.num_hosts if spec is not None else 1
        return {
            'cluster_name': cluster_name,
            'region': region.name,
            'zones': [z.name for z in (zones or [])],
            'tpu': spec is not None,
            'tpu_accelerator_type': spec.name if spec else None,
            'tpu_topology': spec.topology_str if spec else None,
            'tpu_num_hosts': num_hosts,
            'tpu_num_chips': spec.num_chips if spec else 0,
            'num_slices': resources.num_slices,
            'use_spot': resources.use_spot,
            'instance_type': resources.instance_type or 'local',
        }

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        return True, None

    def get_current_user_identity(self) -> Optional[List[str]]:
        from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
        return [common_utils.get_user_hash()]
