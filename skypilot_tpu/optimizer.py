"""Optimizer: choose the cheapest/fastest feasible placement per task.

Parity: /root/reference/sky/optimizer.py:76-1340 (`Optimizer.optimize`,
launchable enumeration via `cloud.get_feasible_launchable_resources`,
cost/time estimation, DP over chain DAGs, egress modeling, plan table).
Differences from the reference:

* TPU slices and GPU VMs are fungible candidates in one search — the
  BASELINE.json north star. A throughput prior (`_relative_throughput`)
  based on aggregate bf16 TFLOPs makes $/work comparable across
  accelerator families when no user `time_estimator` is given.
* General (non-chain) DAGs are optimized without the reference's pulp
  ILP (optimizer.py:470): exact product-space search when the space is
  small, else greedy + coordinate-descent local search.  Execution
  remains chain-only (same restriction as the reference's launch /
  managed-jobs paths) — the guard lives in the execution layer, not
  here, mirroring the reference split.
"""
from __future__ import annotations

import collections
import enum
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.clouds import registry
from skypilot_tpu.resources import Resources
from skypilot_tpu.utils import accelerator_registry

logger = sky_logging.init_logger(__name__)

# Seconds assumed per task when no time estimator is set: cost comparisons
# then reduce to $/hr × relative-throughput.
_DEFAULT_RUNTIME_SECONDS = 3600.0
# General-DAG search: exhaustive (exact) below this assignment-space
# size, coordinate-descent local search above it.
_EXACT_LIMIT = 20_000


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


def _enabled_clouds() -> List[str]:
    enabled = global_user_state.get_enabled_clouds()
    if not enabled:
        raise exceptions.NoCloudAccessError(
            'No infra enabled. Run `sky check` first.')
    return enabled


def _relative_throughput(resources: Resources) -> float:
    """Throughput prior for cross-accelerator TIME estimates.

    Effective (sustained) TFLOPs = peak dense-bf16 TFLOPs x MFU, where
    the MFU factor is MEASURED when a bench has run on that accelerator
    (utils/throughput_registry; bench.py records its result) and a
    conservative family default otherwise (SURVEY.md §7 'optimizer
    fungibility'; user `set_time_estimator` hints override entirely).
    """
    from skypilot_tpu.utils import throughput_registry  # pylint: disable=import-outside-toplevel
    spec = resources.tpu_spec
    if spec is not None:
        key = f'tpu-{spec.generation}'
        return (spec.total_bf16_tflops * resources.num_slices *
                throughput_registry.mfu_for(key))
    accs = resources.accelerators
    if accs:
        name, count = next(iter(accs.items()))
        gpu_tflops = {
            'A100': 312.0, 'A100-80GB': 312.0, 'H100': 989.0,
            'H100-MEGA': 989.0, 'A10G': 125.0, 'L4': 121.0, 'T4': 65.0,
            'V100': 125.0, 'P100': 21.0, 'K80': 8.7,
        }.get(name, 50.0)
        return gpu_tflops * count * throughput_registry.mfu_for(name)
    return 1.0


class Optimizer:
    """Per-task launchable search + DAG-level plan selection."""

    @staticmethod
    def optimize(dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[List[Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        """Fill in `task.best_resources` for every task in the dag."""
        if dag.is_chain():
            plan = _optimize_chain_by_dp(dag, minimize, blocked_resources)
        else:
            plan = _optimize_general(dag, minimize, blocked_resources)
        for task, (resources, _) in plan.items():
            task.best_resources = resources
        if not quiet:
            logger.info(format_plan_table(plan, minimize))
        return dag

    @staticmethod
    def enumerate_launchables(
        task: task_lib.Task,
        blocked_resources: Optional[List[Resources]] = None,
    ) -> List[Tuple[Resources, float]]:
        """All feasible (launchable, $/hr) for a task, cheapest first.

        Parity: reference `_fill_in_launchable_resources`
        (optimizer.py:1255).
        """
        enabled = _enabled_clouds()
        candidates: List[Tuple[Resources, float]] = []
        fuzzy: List[str] = []
        for requested in task.resources:
            clouds = ([requested.cloud] if requested.cloud is not None else
                      [registry.from_str(name) for name in enabled])
            for cloud in clouds:
                if cloud is None or cloud.name not in enabled:
                    continue
                launchables, cloud_fuzzy = (
                    cloud.get_feasible_launchable_resources(requested))
                fuzzy.extend(cloud_fuzzy)
                for launchable in launchables:
                    if _is_blocked(launchable, blocked_resources):
                        continue
                    hourly = launchable.get_cost(3600.0)
                    candidates.append((launchable, hourly))
        candidates.sort(key=lambda pair: pair[1])
        if not candidates:
            hint = ''
            if fuzzy:
                hint = f' Did you mean one of: {sorted(set(fuzzy))[:8]}?'
            raise exceptions.ResourcesUnavailableError(
                f'No feasible resources for task {task.name!r} on enabled '
                f'infra {enabled}.{hint}')
        return candidates

    # Kept as the reference spells it, for familiarity.
    optimize_dag = optimize


def _is_blocked(launchable: Resources,
                blocked_resources: Optional[List[Resources]]) -> bool:
    if not blocked_resources:
        return False
    return any(blocked.less_demanding_than(launchable) and
               launchable.less_demanding_than(blocked)
               for blocked in blocked_resources)


def _estimate(task: task_lib.Task, resources: Resources,
              minimize: OptimizeTarget) -> Tuple[float, float]:
    """→ (cost USD, runtime seconds) for running `task` on `resources`."""
    try:
        runtime = task.estimate_runtime(resources)
    except exceptions.InvalidTaskError:
        if minimize is OptimizeTarget.TIME:
            # Scale the default runtime by the throughput prior so TIME
            # search prefers bigger aggregate FLOPs.
            runtime = (_DEFAULT_RUNTIME_SECONDS * 100.0 /
                       max(_relative_throughput(resources), 1e-9))
        else:
            runtime = _DEFAULT_RUNTIME_SECONDS
    cost = resources.get_cost(runtime) * task.num_nodes
    return cost, runtime


def _egress_metrics(src: Optional[Resources], dst: Resources,
                    gigabytes: Optional[float]) -> Tuple[float, float]:
    """(egress cost, egress seconds) between consecutive chain tasks.

    Parity: reference optimizer.py:76-105. Same-cloud transfer is free;
    cross-cloud pays the source cloud's egress rate at an assumed 10 Gbps.
    """
    if src is None or gigabytes is None or gigabytes <= 0:
        return 0.0, 0.0
    if src.cloud == dst.cloud:
        return 0.0, 0.0
    assert src.cloud is not None
    cost = src.cloud.get_egress_cost(gigabytes)
    seconds = gigabytes * 8 / 10.0  # 10 Gbps
    return cost, seconds


def _optimize_chain_by_dp(
    dag: dag_lib.Dag,
    minimize: OptimizeTarget,
    blocked_resources: Optional[List[Resources]],
) -> 'collections.OrderedDict[task_lib.Task, Tuple[Resources, float]]':
    """Topological DP over the chain (parity optimizer.py:409)."""
    order = dag.topological_order()
    # dp[resources] = (objective so far, cost so far, runtime so far, parent)
    prev_dp: Dict[Resources, Tuple[float, float, float, Optional[Resources]]] = {
        None: (0.0, 0.0, 0.0, None)}  # type: ignore[dict-item]
    choices: List[Tuple[task_lib.Task, List[Tuple[Resources, float, float]]]] = []
    parents: List[Dict[Resources, Optional[Resources]]] = []

    prev_task: Optional[task_lib.Task] = None
    for task in order:
        launchables = Optimizer.enumerate_launchables(task, blocked_resources)
        dp: Dict[Resources, Tuple[float, float, float, Optional[Resources]]] = {}
        parent_of: Dict[Resources, Optional[Resources]] = {}
        per_task: List[Tuple[Resources, float, float]] = []
        for resources, _ in launchables:
            cost, runtime = _estimate(task, resources, minimize)
            per_task.append((resources, cost, runtime))
            best_obj = None
            best_entry = None
            best_parent = None
            for parent_res, (_, pcost, ptime, _) in prev_dp.items():
                egress_gb = (prev_task.estimated_outputs_size_gigabytes
                             if prev_task is not None else None)
                ecost, etime = _egress_metrics(parent_res, resources, egress_gb)
                total_cost = pcost + cost + ecost
                total_time = ptime + runtime + etime
                obj = total_cost if minimize is OptimizeTarget.COST else total_time
                if best_obj is None or obj < best_obj:
                    best_obj = obj
                    best_entry = (obj, total_cost, total_time)
                    best_parent = parent_res
            assert best_entry is not None
            dp[resources] = (*best_entry, best_parent)
            parent_of[resources] = best_parent
        choices.append((task, per_task))
        parents.append(parent_of)
        prev_dp = dp
        prev_task = task

    # Backtrack from the best terminal entry.
    best_final = min(prev_dp.items(), key=lambda kv: kv[1][0])
    plan_rev: List[Tuple[task_lib.Task, Resources]] = []
    cursor: Optional[Resources] = best_final[0]
    for (task, _), parent_of in zip(reversed(choices), reversed(parents)):
        assert cursor is not None
        plan_rev.append((task, cursor))
        cursor = parent_of[cursor]

    plan: 'collections.OrderedDict[task_lib.Task, Tuple[Resources, float]]' = (
        collections.OrderedDict())
    for task, resources in reversed(plan_rev):
        cost, _ = _estimate(task, resources, minimize)
        plan[task] = (resources, cost)
    return plan


def _optimize_general(
    dag: dag_lib.Dag,
    minimize: OptimizeTarget,
    blocked_resources: Optional[List[Resources]],
) -> 'collections.OrderedDict[task_lib.Task, Tuple[Resources, float]]':
    """Assignment search for general (non-chain) DAGs.

    Parity: reference `_optimize_by_ilp` (optimizer.py:470, pulp).
    Objective: COST = Σ task cost + Σ edge egress cost; TIME = the
    DAG's critical-path latency (per-task runtime + edge egress time).
    Exact when the assignment space is small (≤ _EXACT_LIMIT points),
    else greedy-init + coordinate descent, which is exact per-task
    given the rest of the assignment and converges in a few sweeps.
    """
    order = dag.topological_order()
    cands: Dict[task_lib.Task, List[Tuple[Resources, float, float]]] = {}
    for task in order:
        cands[task] = [
            (res, *_estimate(task, res, minimize))
            for res, _ in Optimizer.enumerate_launchables(
                task, blocked_resources)
        ]

    parents = {task: dag.predecessors(task) for task in order}

    def objective(assign: Dict[task_lib.Task, int]) -> float:
        total_cost = 0.0
        finish: Dict[task_lib.Task, float] = {}
        for task in order:
            res, cost, runtime = cands[task][assign[task]]
            total_cost += cost
            start = 0.0
            for parent in parents[task]:
                pres = cands[parent][assign[parent]][0]
                ecost, etime = _egress_metrics(
                    pres, res, parent.estimated_outputs_size_gigabytes)
                total_cost += ecost
                start = max(start, finish[parent] + etime)
            finish[task] = start + runtime
        if minimize is OptimizeTarget.TIME:
            return max(finish.values()) if finish else 0.0
        return total_cost

    sizes = [len(cands[t]) for t in order]
    space = 1
    for s in sizes:
        space *= s

    if space <= _EXACT_LIMIT:
        # Exhaustive product-space search (exact, like the ILP).
        import itertools  # pylint: disable=import-outside-toplevel
        best_assign = None
        best_obj = None
        for combo in itertools.product(*(range(s) for s in sizes)):
            assign = dict(zip(order, combo))
            obj = objective(assign)
            if best_obj is None or obj < best_obj:
                best_obj, best_assign = obj, assign
        assert best_assign is not None
        assign = best_assign
    else:
        # Greedy: each task's independently best candidate by TOTAL
        # task cost/runtime (hourly-price order is not total-cost order
        # once a time estimator scales runtimes).
        metric = 2 if minimize is OptimizeTarget.TIME else 1
        assign = {
            task: min(range(len(cands[task])),
                      key=lambda i, t=task: cands[t][i][metric])
            for task in order
        }
        # Coordinate descent: re-pick one task at a time against the
        # rest until a full sweep makes no improvement.  The COST
        # objective is separable (task cost + incident-edge egress), so
        # a move is scored by its O(degree) delta; TIME (critical path)
        # is not separable and pays the full DAG walk per move.
        children: Dict[task_lib.Task, List[task_lib.Task]] = {
            t: [] for t in order}
        for task in order:
            for parent in parents[task]:
                children[parent].append(task)

        def move_cost(task: task_lib.Task, i: int) -> float:
            """Task i's cost + egress on every incident edge, given the
            rest of `assign` (COST objective only)."""
            res, cost, _ = cands[task][i]
            total = cost
            for parent in parents[task]:
                pres = cands[parent][assign[parent]][0]
                total += _egress_metrics(
                    pres, res, parent.estimated_outputs_size_gigabytes)[0]
            for child in children[task]:
                cres = cands[child][assign[child]][0]
                total += _egress_metrics(
                    res, cres, task.estimated_outputs_size_gigabytes)[0]
            return total

        is_cost = minimize is OptimizeTarget.COST
        best_obj = objective(assign)
        for _ in range(10):  # sweeps; converges in 2-3 in practice
            improved = False
            for task in order:
                current = assign[task]
                if is_cost:
                    base = move_cost(task, current)
                for i in range(len(cands[task])):
                    if i == current:
                        continue
                    if is_cost:
                        cand_cost = move_cost(task, i)
                        if cand_cost < base - 1e-12:
                            assign[task] = i
                            best_obj += cand_cost - base
                            current = i
                            base = cand_cost
                            improved = True
                        continue
                    assign[task] = i
                    obj = objective(assign)
                    if obj < best_obj - 1e-12:
                        best_obj = obj
                        current = i
                        improved = True
                assign[task] = current
            if not improved:
                break

    plan: 'collections.OrderedDict[task_lib.Task, Tuple[Resources, float]]' = (
        collections.OrderedDict())
    for task in order:
        res, cost, _ = cands[task][assign[task]]
        plan[task] = (res, cost)
    return plan


def format_plan_table(
        plan: 'collections.OrderedDict[task_lib.Task, Tuple[Resources, float]]',
        minimize: OptimizeTarget) -> str:
    """Human-readable plan summary (parity optimizer.py:718 pretty table).

    TFLOPS is the candidate's EFFECTIVE throughput (peak x MFU; `*`
    marks a bench-MEASURED MFU rather than a family default).
    EST.TIME is printed only when the task carries a real
    `set_time_estimator` — never a fabricated absolute from the
    default-runtime scalar.
    """
    from skypilot_tpu.utils import throughput_registry  # pylint: disable=import-outside-toplevel
    lines = [f'Optimizer target: {minimize.value.upper()}', '']
    header = (f'{"TASK":<20} {"RESOURCES":<42} {"$/HR":>8} {"HOSTS":>6} '
              f'{"TFLOPS":>9} {"EST.TIME":>9}')
    lines.append(header)
    lines.append('-' * len(header))
    any_measured = False
    for task, (resources, _) in plan.items():
        hourly = resources.get_cost(3600.0) * task.num_nodes
        spec = resources.tpu_spec
        label = repr(resources)[len('<Resources: '):-1]
        hosts = (spec.num_hosts * resources.num_slices
                 if spec is not None else 1) * task.num_nodes
        if spec is not None:
            measured = throughput_registry.is_measured(
                f'tpu-{spec.generation}')
        elif resources.accelerators:
            measured = throughput_registry.is_measured(
                next(iter(resources.accelerators)))
        else:
            measured = False
        any_measured |= measured
        tflops = (f'{_relative_throughput(resources):.0f}'
                  + ('*' if measured else ''))
        try:
            est = f'{task.estimate_runtime(resources) / 3600.0:.1f}h'
        except exceptions.InvalidTaskError:
            est = '-'
        lines.append(f'{(task.name or "-")[:20]:<20} {label:<42} '
                     f'{hourly:>8.2f} {hosts:>6} {tflops:>9} {est:>9}')
    if any_measured:
        lines.append('* = effective TFLOPs from a measured bench MFU')
    return '\n'.join(lines)


# ----------------------------------------------------- multi-region placement

# Per-region TPU serving catalog: relative $/chip-hr (1.0 = the
# cheapest region's on-demand price) and an availability score in
# (0, 1] (how often capacity requests succeed — the stockout signal
# preemption telemetry feeds in real deployments).  Override/extend
# with SKYTPU_REGION_CATALOG (JSON of the same shape).
REGION_CATALOG: Dict[str, Dict[str, float]] = {
    'us-central1': {'cost': 1.00, 'availability': 0.97},
    'us-east1': {'cost': 1.04, 'availability': 0.93},
    'europe-west4': {'cost': 1.10, 'availability': 0.95},
    'asia-east1': {'cost': 1.18, 'availability': 0.90},
}


def region_catalog() -> Dict[str, Dict[str, float]]:
    """The region catalog with SKYTPU_REGION_CATALOG overrides merged
    in (unknown/malformed entries ignored — placement must not fail on
    a bad override)."""
    import json  # pylint: disable=import-outside-toplevel
    import os  # pylint: disable=import-outside-toplevel
    catalog = {name: dict(entry)
               for name, entry in REGION_CATALOG.items()}
    raw = os.environ.get('SKYTPU_REGION_CATALOG')
    if raw:
        try:
            override = json.loads(raw)
        except json.JSONDecodeError:
            override = None
        if isinstance(override, dict):
            for name, entry in override.items():
                if not isinstance(entry, dict):
                    continue
                merged = catalog.setdefault(
                    str(name), {'cost': 1.0, 'availability': 0.9})
                for key in ('cost', 'availability'):
                    if entry.get(key) is not None:
                        try:
                            merged[key] = float(entry[key])
                        except (TypeError, ValueError):
                            pass
    return catalog


def rank_regions(catalog: Optional[Dict[str, Dict[str, float]]] = None
                 ) -> List[str]:
    """Regions best-first by availability-per-dollar (an unavailable
    cheap region loses to a slightly pricier one that actually has
    chips); name-ordered tiebreak keeps the ranking deterministic."""
    catalog = catalog if catalog is not None else region_catalog()
    def score(name: str) -> float:
        entry = catalog[name]
        cost = max(float(entry.get('cost', 1.0)), 1e-6)
        return float(entry.get('availability', 0.9)) / cost
    return sorted(catalog, key=lambda name: (-score(name), name))


def place_role_pools(spec) -> Dict[str, List[str]]:
    """Region placement per role pool of a service spec.

    Pools that can run >= 2 replicas get the TOP TWO regions (survive a
    full-region loss: the router tier's cross-region failover needs a
    same-role replica somewhere else); single-replica pools take the
    best region only.  Replicas round-robin over the returned list, so
    a 4-replica pool lands 2+2 across the pair."""
    plan: Dict[str, List[str]] = {}
    ranked = rank_regions()
    if not ranked:
        return plan
    for role, pool in getattr(spec, 'role_specs', {}).items():
        width = 2 if getattr(pool, 'max_replicas', 1) >= 2 else 1
        plan[role] = ranked[:max(1, min(width, len(ranked)))]
    return plan


def format_region_plan(plan: Dict[str, List[str]]) -> str:
    """Human-readable multi-region placement summary (the serve-side
    sibling of format_plan_table)."""
    catalog = region_catalog()
    lines = ['Multi-region placement:', '']
    header = f'{"ROLE":<12} {"REGIONS":<40} {"REL.$":>6} {"AVAIL":>6}'
    lines.append(header)
    lines.append('-' * len(header))
    for role, regions in sorted(plan.items()):
        costs = [catalog.get(r, {}).get('cost', 1.0) for r in regions]
        avail = [catalog.get(r, {}).get('availability', 0.9)
                 for r in regions]
        mean_cost = sum(costs) / len(costs) if costs else 1.0
        min_avail = min(avail) if avail else 0.0
        lines.append(f'{role[:12]:<12} {", ".join(regions):<40} '
                     f'{mean_cost:>6.2f} {min_avail:>6.2f}')
    return '\n'.join(lines)
