"""Backend helpers: per-cluster locking + cluster status reconciliation.

Parity: /root/reference/sky/backends/backend_utils.py:1669-2004
(`_update_cluster_status_no_lock`, `refresh_cluster_status_handle`) and
the per-cluster FileLock the reference holds around provision/teardown
(/root/reference/sky/backends/cloud_vm_ray_backend.py:2729-2731).

Reconciliation is two-phase, like the reference: the cloud API gives the
instance view, but "all hosts UP" is necessary, not sufficient — an UP
record is only confirmed UP if the skylet daemon on the head host
answers a liveness probe over ssh (the reference probes `ray status`
the same way, backend_utils.py:1669).  The all-or-nothing slice model
simplifies the drift matrix: any partial state degrades to INIT.
"""
from __future__ import annotations

import contextlib
import os
import typing
from typing import Any, Dict, Iterator, List, Optional

import filelock

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu import status_lib
from skypilot_tpu.skylet import constants
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import timeline

if typing.TYPE_CHECKING:
    from skypilot_tpu.backends import slice_backend

logger = sky_logging.init_logger(__name__)

# How long a status refresh waits for a cluster lock before giving up
# and returning the cached record (someone else is mutating the
# cluster; their final state lands in the DB anyway).
_STATUS_LOCK_TIMEOUT_SECONDS = 10.0
_SKYLET_PROBE_CMD = (
    f'test -f {constants.SKYLET_PID_FILE} && '
    f'kill -0 "$(cat {constants.SKYLET_PID_FILE})" 2>/dev/null')


class SSHConfigHelper:
    """`ssh <cluster>` UX: managed Host blocks in the user's ssh config.

    Parity: /root/reference/sky/backends/backend_utils.py:399
    (SSHConfigHelper).  Per-cluster config files live under
    $SKYTPU_HOME/ssh/<cluster>.conf; one managed `Include` line at the
    TOP of ~/.ssh/config pulls them in (Include must precede the first
    Host block to apply globally).  `ssh <cluster>` reaches the head
    host; workers are `<cluster>-worker1..N`.
    """

    _INCLUDE_MARK = '# Added by skypilot_tpu'

    @classmethod
    def _ssh_dir(cls) -> str:
        return common_utils.ensure_dir(
            os.path.join(common_utils.skytpu_home(), 'ssh'), mode=0o700)

    @classmethod
    def _cluster_conf_path(cls, cluster_name: str) -> str:
        return os.path.join(cls._ssh_dir(), f'{cluster_name}.conf')

    @classmethod
    def _ensure_include(cls) -> None:
        config_path = os.path.expanduser('~/.ssh/config')
        include_line = f'Include {cls._ssh_dir()}/*.conf'
        content = ''
        if os.path.exists(config_path):
            with open(config_path, encoding='utf-8') as f:
                content = f.read()
        if include_line in content:
            return
        os.makedirs(os.path.dirname(config_path), mode=0o700,
                    exist_ok=True)
        new = (f'{cls._INCLUDE_MARK}\n{include_line}\n\n' + content)
        with open(config_path, 'w', encoding='utf-8') as f:
            f.write(new)
        os.chmod(config_path, 0o600)

    @classmethod
    def add_cluster(cls, cluster_name: str, ips: List[str], *,
                    ssh_user: str, ssh_private_key: Optional[str],
                    port: int = 22,
                    ssh_proxy_command: Optional[str] = None) -> None:
        if not ips:
            return
        cls._ensure_include()
        blocks = []
        for i, ip in enumerate(ips):
            host = cluster_name if i == 0 else f'{cluster_name}-worker{i}'
            lines = [
                f'Host {host}',
                f'  HostName {ip}',
                f'  User {ssh_user}',
                f'  Port {port}',
                '  StrictHostKeyChecking no',
                '  UserKnownHostsFile /dev/null',
                '  IdentitiesOnly yes',
            ]
            if ssh_private_key:
                lines.append(f'  IdentityFile {ssh_private_key}')
            if ssh_proxy_command:
                lines.append(f'  ProxyCommand {ssh_proxy_command}')
            blocks.append('\n'.join(lines))
        path = cls._cluster_conf_path(cluster_name)
        with open(path, 'w', encoding='utf-8') as f:
            f.write(f'{cls._INCLUDE_MARK}: cluster {cluster_name}\n'
                    + '\n\n'.join(blocks) + '\n')
        os.chmod(path, 0o600)
        logger.debug(f'ssh config written for {cluster_name} '
                     f'({len(ips)} host(s)).')

    @classmethod
    def remove_cluster(cls, cluster_name: str) -> None:
        try:
            os.remove(cls._cluster_conf_path(cluster_name))
        except OSError:
            pass

    @classmethod
    def list_clusters(cls) -> List[str]:
        try:
            return sorted(
                f[:-len('.conf')] for f in os.listdir(cls._ssh_dir())
                if f.endswith('.conf'))
        except OSError:
            return []


def check_remote_runtime_version(
        handle: 'slice_backend.SliceResourceHandle') -> Optional[str]:
    """Client/remote version-skew check (reference backend_utils.py:2593;
    policy codified from tests/backward_compatibility_tests.sh).

    The handle records the client version that shipped the app tree at
    provision time (`launched_runtime_version`), so the check is a
    LOCAL comparison — no per-exec ssh round-trip on the
    time-to-first-step hot path.

    Skew policy:
    - same version → None (silent);
    - same MAJOR (minor/patch drift) → warning string: the job codegen
      and wire contract are stable within a major, exec proceeds;
    - different MAJOR → RuntimeVersionSkewError: the contract may have
      changed; exec refuses until a relaunch resyncs the runtime.
      Read-only verbs (status/queue/logs) never call this check — an
      old cluster stays inspectable from any client.
    - unknowable (pre-stamp handle / dev tree) → None.
    """
    from skypilot_tpu import exceptions  # pylint: disable=import-outside-toplevel
    import skypilot_tpu  # pylint: disable=import-outside-toplevel
    local_version = getattr(skypilot_tpu, '__version__', None)
    remote_version = getattr(handle, 'launched_runtime_version', None)
    if local_version is None or remote_version is None:
        return None
    if remote_version == local_version:
        return None

    def _major(version: str) -> Optional[str]:
        head = version.split('.', 1)[0]
        return head if head.isdigit() else None

    resync_hint = ('relaunch the cluster (`sky launch` on the same '
                   'name) to resync the runtime.')
    local_major, remote_major = _major(local_version), _major(
        remote_version)
    if (local_major is None or remote_major is None or
            local_major == remote_major):
        return (f'Cluster {handle.cluster_name} runs skypilot_tpu '
                f'{remote_version}, client is {local_version}; '
                f'{resync_hint}')
    raise exceptions.RuntimeVersionSkewError(
        f'Cluster {handle.cluster_name} runs skypilot_tpu '
        f'{remote_version}; this client is {local_version} — a major '
        f'version apart, so the job wire contract may differ. '
        f'Refusing to exec; {resync_hint}')


def cluster_lock_path(cluster_name: str) -> str:
    lock_dir = common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'locks'))
    return os.path.join(lock_dir, f'{cluster_name}.lock')


@contextlib.contextmanager
def cluster_file_lock(cluster_name: str,
                      timeout: float = -1) -> Iterator[None]:
    """Per-cluster advisory lock serializing provision/teardown/status
    transitions across processes.  timeout<0 waits forever; raises
    filelock.Timeout otherwise."""
    path = cluster_lock_path(cluster_name)
    with timeline.FileLockEvent(path, timeout=timeout):
        yield


def probe_skylet(handle: 'slice_backend.SliceResourceHandle') -> bool:
    """True iff the skylet daemon on the head host is alive (over ssh)."""
    try:
        runners = handle.get_command_runners()
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'probe_skylet: no runners for '
                     f'{handle.cluster_name}: {e}')
        return False
    if not runners:
        return False
    try:
        rc = runners[0].run(_SKYLET_PROBE_CMD, stream_logs=False)
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'probe_skylet: probe failed for '
                     f'{handle.cluster_name}: {e}')
        return False
    return rc == 0


def _reconcile(record: Dict[str, Any],
               cloud_statuses: List[Optional[status_lib.ClusterStatus]],
               probe_runtime: bool) -> Optional[status_lib.ClusterStatus]:
    """The drift matrix: (recorded status, cloud view) -> new status.

    Returns None when the cluster should be removed from the records.
    """
    recorded = record['status']
    handle = record['handle']
    if all(s is None for s in cloud_statuses):
        # Vanished: the cloud has no trace of any host.
        return None
    if all(s == status_lib.ClusterStatus.UP for s in cloud_statuses):
        if recorded == status_lib.ClusterStatus.UP:
            # UP-but-dead-skylet: ssh probe decides whether the runtime
            # is actually healthy.
            if probe_runtime and not probe_skylet(handle):
                logger.warning(
                    f'Cluster {record["name"]!r}: hosts are up but the '
                    'skylet is unreachable; marking INIT.')
                return status_lib.ClusterStatus.INIT
            return status_lib.ClusterStatus.UP
        # STOPPED-but-running, WAITING-granted, or half-finished launch:
        # hosts exist but the runtime was never confirmed — INIT until a
        # launch re-runs runtime setup.
        return status_lib.ClusterStatus.INIT
    if all(s == status_lib.ClusterStatus.STOPPED for s in cloud_statuses):
        return status_lib.ClusterStatus.STOPPED
    # Partial slice (mixed up/stopped/missing): abnormal by the
    # all-or-nothing slice model.
    return status_lib.ClusterStatus.INIT


def refresh_cluster_status(
        cluster_name: str,
        *,
        probe_runtime: bool = True,
        acquire_lock: bool = True) -> Optional[status_lib.ClusterStatus]:
    """Reconcile recorded status with the provider's live view.

    Returns the (possibly updated) status, or None if the cluster no
    longer exists anywhere.
    """
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    handle = record['handle']
    if handle is None:
        return record['status']

    if acquire_lock:
        try:
            with cluster_file_lock(cluster_name,
                                   timeout=_STATUS_LOCK_TIMEOUT_SECONDS):
                return refresh_cluster_status(cluster_name,
                                              probe_runtime=probe_runtime,
                                              acquire_lock=False)
        except filelock.Timeout:
            logger.debug(f'{cluster_name}: status lock busy; returning '
                         'cached status.')
            return record['status']

    try:
        statuses = provision.query_instances(handle.provider_name,
                                             cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Status query failed for {cluster_name}: {e}')
        return record['status']

    if not statuses:
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return None
    new_status = _reconcile(record, list(statuses.values()), probe_runtime)
    if new_status is None:
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return None
    if new_status != record['status']:
        global_user_state.set_cluster_status(cluster_name, new_status)
    return new_status


def refresh_cluster_record(cluster_name: str) -> Optional[Dict[str, Any]]:
    status = refresh_cluster_status(cluster_name)
    if status is None:
        return None
    return global_user_state.get_cluster_from_name(cluster_name)


def check_cluster_available(
        cluster_name: str) -> 'slice_backend.SliceResourceHandle':
    """Raise unless the cluster exists and is UP; returns its handle.

    Parity: reference backend_utils check_cluster_available
    (execution.py:547 call site).
    """
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    # No skylet probe here: the caller is about to ssh anyway and fails
    # fast if the runtime is dead; probing would double every
    # exec/queue/logs round-trip.  Explicit `status --refresh` and the
    # launch reuse-decision keep the probe.
    status = refresh_cluster_status(cluster_name, probe_runtime=False)
    if status is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} no longer exists on the cloud.')
    if status != status_lib.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {status.value}, not UP.',
            cluster_status=status, handle=record['handle'])
    if record['handle'] is None:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} has no handle (launch in progress?).',
            cluster_status=status)
    return record['handle']


def get_clusters(refresh: bool = False,
                 cluster_names: Optional[List[str]] = None
                 ) -> List[Dict[str, Any]]:
    records = global_user_state.get_clusters()
    if cluster_names is not None:
        wanted = set()
        for pattern in cluster_names:
            wanted.update(global_user_state.get_glob_cluster_names(pattern))
        records = [r for r in records if r['name'] in wanted]
    if not refresh:
        return records
    refreshed = []
    for record in records:
        new_record = refresh_cluster_record(record['name'])
        if new_record is not None:
            refreshed.append(new_record)
    return refreshed
