"""Backend helpers: cluster status refresh — the state reconciler.

Parity: /root/reference/sky/backends/backend_utils.py:1669-2004
(`_update_cluster_status_no_lock`, `refresh_cluster_status_handle`) — 230
lines of subtlety in the reference, simplified here by the all-or-nothing
slice model: a slice is UP only if *every* host is up; any mix is abnormal
and degrades to INIT (or removal if the cloud says everything is gone).
"""
from __future__ import annotations

import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu import status_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu.backends import slice_backend

logger = sky_logging.init_logger(__name__)


def refresh_cluster_status(
        cluster_name: str) -> Optional[status_lib.ClusterStatus]:
    """Reconcile recorded status with the provider's live view.

    Returns the (possibly updated) status, or None if the cluster no longer
    exists anywhere.
    """
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    handle = record['handle']
    if handle is None:
        return record['status']
    try:
        statuses = provision.query_instances(handle.provider_name,
                                             cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Status query failed for {cluster_name}: {e}')
        return record['status']

    if not statuses:
        # The cloud has no trace of it: cluster is gone.
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return None
    values = list(statuses.values())
    if all(s == status_lib.ClusterStatus.UP for s in values):
        new_status = (record['status']
                      if record['status'] in (status_lib.ClusterStatus.INIT,
                                              status_lib.ClusterStatus.UP)
                      else status_lib.ClusterStatus.INIT)
        if record['status'] == status_lib.ClusterStatus.UP:
            new_status = status_lib.ClusterStatus.UP
        elif record['status'] == status_lib.ClusterStatus.WAITING:
            # Queued capacity got granted behind our back.
            new_status = status_lib.ClusterStatus.INIT
    elif all(s == status_lib.ClusterStatus.STOPPED for s in values):
        new_status = status_lib.ClusterStatus.STOPPED
    elif all(s is None for s in values):
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return None
    else:
        # Partial slice (some hosts up, some stopped/preempted): abnormal.
        new_status = status_lib.ClusterStatus.INIT
    if new_status != record['status']:
        global_user_state.set_cluster_status(cluster_name, new_status)
    return new_status


def refresh_cluster_record(cluster_name: str) -> Optional[Dict[str, Any]]:
    status = refresh_cluster_status(cluster_name)
    if status is None:
        return None
    return global_user_state.get_cluster_from_name(cluster_name)


def check_cluster_available(
        cluster_name: str) -> 'slice_backend.SliceResourceHandle':
    """Raise unless the cluster exists and is UP; returns its handle.

    Parity: reference backend_utils check_cluster_available
    (execution.py:547 call site).
    """
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    status = refresh_cluster_status(cluster_name)
    if status is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} no longer exists on the cloud.')
    if status != status_lib.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {status.value}, not UP.',
            cluster_status=status, handle=record['handle'])
    if record['handle'] is None:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} has no handle (launch in progress?).',
            cluster_status=status)
    return record['handle']


def get_clusters(refresh: bool = False,
                 cluster_names: Optional[List[str]] = None
                 ) -> List[Dict[str, Any]]:
    records = global_user_state.get_clusters()
    if cluster_names is not None:
        wanted = set()
        for pattern in cluster_names:
            wanted.update(global_user_state.get_glob_cluster_names(pattern))
        records = [r for r in records if r['name'] in wanted]
    if not refresh:
        return records
    refreshed = []
    for record in records:
        new_record = refresh_cluster_record(record['name'])
        if new_record is not None:
            refreshed.append(new_record)
    return refreshed
