"""Backend helpers: per-cluster locking + cluster status reconciliation.

Parity: /root/reference/sky/backends/backend_utils.py:1669-2004
(`_update_cluster_status_no_lock`, `refresh_cluster_status_handle`) and
the per-cluster FileLock the reference holds around provision/teardown
(/root/reference/sky/backends/cloud_vm_ray_backend.py:2729-2731).

Reconciliation is two-phase, like the reference: the cloud API gives the
instance view, but "all hosts UP" is necessary, not sufficient — an UP
record is only confirmed UP if the skylet daemon on the head host
answers a liveness probe over ssh (the reference probes `ray status`
the same way, backend_utils.py:1669).  The all-or-nothing slice model
simplifies the drift matrix: any partial state degrades to INIT.
"""
from __future__ import annotations

import contextlib
import os
import typing
from typing import Any, Dict, Iterator, List, Optional

import filelock

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu import status_lib
from skypilot_tpu.skylet import constants
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import timeline

if typing.TYPE_CHECKING:
    from skypilot_tpu.backends import slice_backend

logger = sky_logging.init_logger(__name__)

# How long a status refresh waits for a cluster lock before giving up
# and returning the cached record (someone else is mutating the
# cluster; their final state lands in the DB anyway).
_STATUS_LOCK_TIMEOUT_SECONDS = 10.0
_SKYLET_PROBE_CMD = (
    f'test -f {constants.SKYLET_PID_FILE} && '
    f'kill -0 "$(cat {constants.SKYLET_PID_FILE})" 2>/dev/null')


def cluster_lock_path(cluster_name: str) -> str:
    lock_dir = common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'locks'))
    return os.path.join(lock_dir, f'{cluster_name}.lock')


@contextlib.contextmanager
def cluster_file_lock(cluster_name: str,
                      timeout: float = -1) -> Iterator[None]:
    """Per-cluster advisory lock serializing provision/teardown/status
    transitions across processes.  timeout<0 waits forever; raises
    filelock.Timeout otherwise."""
    path = cluster_lock_path(cluster_name)
    with timeline.FileLockEvent(path, timeout=timeout):
        yield


def probe_skylet(handle: 'slice_backend.SliceResourceHandle') -> bool:
    """True iff the skylet daemon on the head host is alive (over ssh)."""
    try:
        runners = handle.get_command_runners()
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'probe_skylet: no runners for '
                     f'{handle.cluster_name}: {e}')
        return False
    if not runners:
        return False
    try:
        rc = runners[0].run(_SKYLET_PROBE_CMD, stream_logs=False)
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'probe_skylet: probe failed for '
                     f'{handle.cluster_name}: {e}')
        return False
    return rc == 0


def _reconcile(record: Dict[str, Any],
               cloud_statuses: List[Optional[status_lib.ClusterStatus]],
               probe_runtime: bool) -> Optional[status_lib.ClusterStatus]:
    """The drift matrix: (recorded status, cloud view) -> new status.

    Returns None when the cluster should be removed from the records.
    """
    recorded = record['status']
    handle = record['handle']
    if all(s is None for s in cloud_statuses):
        # Vanished: the cloud has no trace of any host.
        return None
    if all(s == status_lib.ClusterStatus.UP for s in cloud_statuses):
        if recorded == status_lib.ClusterStatus.UP:
            # UP-but-dead-skylet: ssh probe decides whether the runtime
            # is actually healthy.
            if probe_runtime and not probe_skylet(handle):
                logger.warning(
                    f'Cluster {record["name"]!r}: hosts are up but the '
                    'skylet is unreachable; marking INIT.')
                return status_lib.ClusterStatus.INIT
            return status_lib.ClusterStatus.UP
        # STOPPED-but-running, WAITING-granted, or half-finished launch:
        # hosts exist but the runtime was never confirmed — INIT until a
        # launch re-runs runtime setup.
        return status_lib.ClusterStatus.INIT
    if all(s == status_lib.ClusterStatus.STOPPED for s in cloud_statuses):
        return status_lib.ClusterStatus.STOPPED
    # Partial slice (mixed up/stopped/missing): abnormal by the
    # all-or-nothing slice model.
    return status_lib.ClusterStatus.INIT


def refresh_cluster_status(
        cluster_name: str,
        *,
        probe_runtime: bool = True,
        acquire_lock: bool = True) -> Optional[status_lib.ClusterStatus]:
    """Reconcile recorded status with the provider's live view.

    Returns the (possibly updated) status, or None if the cluster no
    longer exists anywhere.
    """
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    handle = record['handle']
    if handle is None:
        return record['status']

    if acquire_lock:
        try:
            with cluster_file_lock(cluster_name,
                                   timeout=_STATUS_LOCK_TIMEOUT_SECONDS):
                return refresh_cluster_status(cluster_name,
                                              probe_runtime=probe_runtime,
                                              acquire_lock=False)
        except filelock.Timeout:
            logger.debug(f'{cluster_name}: status lock busy; returning '
                         'cached status.')
            return record['status']

    try:
        statuses = provision.query_instances(handle.provider_name,
                                             cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Status query failed for {cluster_name}: {e}')
        return record['status']

    if not statuses:
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return None
    new_status = _reconcile(record, list(statuses.values()), probe_runtime)
    if new_status is None:
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return None
    if new_status != record['status']:
        global_user_state.set_cluster_status(cluster_name, new_status)
    return new_status


def refresh_cluster_record(cluster_name: str) -> Optional[Dict[str, Any]]:
    status = refresh_cluster_status(cluster_name)
    if status is None:
        return None
    return global_user_state.get_cluster_from_name(cluster_name)


def check_cluster_available(
        cluster_name: str) -> 'slice_backend.SliceResourceHandle':
    """Raise unless the cluster exists and is UP; returns its handle.

    Parity: reference backend_utils check_cluster_available
    (execution.py:547 call site).
    """
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    # No skylet probe here: the caller is about to ssh anyway and fails
    # fast if the runtime is dead; probing would double every
    # exec/queue/logs round-trip.  Explicit `status --refresh` and the
    # launch reuse-decision keep the probe.
    status = refresh_cluster_status(cluster_name, probe_runtime=False)
    if status is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} no longer exists on the cloud.')
    if status != status_lib.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {status.value}, not UP.',
            cluster_status=status, handle=record['handle'])
    if record['handle'] is None:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} has no handle (launch in progress?).',
            cluster_status=status)
    return record['handle']


def get_clusters(refresh: bool = False,
                 cluster_names: Optional[List[str]] = None
                 ) -> List[Dict[str, Any]]:
    records = global_user_state.get_clusters()
    if cluster_names is not None:
        wanted = set()
        for pattern in cluster_names:
            wanted.update(global_user_state.get_glob_cluster_names(pattern))
        records = [r for r in records if r['name'] in wanted]
    if not refresh:
        return records
    refreshed = []
    for record in records:
        new_record = refresh_cluster_record(record['name'])
        if new_record is not None:
            refreshed.append(new_record)
    return refreshed
