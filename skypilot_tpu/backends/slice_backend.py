"""SliceBackend: the orchestration brain, slice-native and Ray-free.

Parity: /root/reference/sky/backends/cloud_vm_ray_backend.py — the
CloudVmRayBackend (:2545), CloudVmRayResourceHandle (:2086),
RetryingVmProvisioner (:1134) and RayCodeGen (:209) collapse here into three
smaller pieces:

* :class:`SliceResourceHandle` — one handle = one slice-cluster = N hosts
  (generalizing `num_ips_per_node`, reference :2475-2483).
* :class:`RetryingProvisioner` — the failover loop over (launchable ×
  region × zone) with a blocklist, re-enumerating candidates through the
  optimizer on exhaustion (parity `provision_with_retries` :1934), plus the
  WAITING path for queued TPU capacity.
* :class:`SliceBackend` — provision/sync/setup/execute/teardown. Execution
  ships a job spec to the head and queues the gang supervisor
  (`backends/gang_supervisor.py`) in the head's job queue; a slice is
  already a gang, so no placement groups and no Ray dependency on hosts.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
import typing
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu import status_lib
from skypilot_tpu.backends import backend as backend_lib
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.clouds import registry
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import provisioner as provisioner_lib
from skypilot_tpu.resources import Resources
from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import job_lib
from skypilot_tpu.skylet import log_lib
from skypilot_tpu.utils import command_runner as command_runner_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

_QUEUED_CAPACITY_TIMEOUT_MINUTES_DEFAULT = 30


class SliceResourceHandle(backend_lib.ResourceHandle):
    """Picklable pointer to one launched slice-cluster."""

    def __init__(self, cluster_name: str, provider_name: str,
                 launched_resources: Resources, launched_nodes: int) -> None:
        self.cluster_name = cluster_name
        self.provider_name = provider_name
        self.launched_resources = launched_resources
        self.launched_nodes = launched_nodes
        # Cached (refreshable) connectivity info.
        self.stable_internal_external_ips: Optional[List[Tuple[str, str]]] = None
        self.launched_at = time.time()
        # Runtime version shipped to the cluster at provision time (the
        # app tree is rsynced then) — lets the skew check compare
        # versions locally, with zero per-exec ssh round-trips.
        import skypilot_tpu  # pylint: disable=import-outside-toplevel
        self.launched_runtime_version = getattr(skypilot_tpu,
                                                '__version__', None)

    def get_cluster_name(self) -> str:
        return self.cluster_name

    @property
    def num_hosts(self) -> int:
        return self.launched_resources.num_hosts * self.launched_nodes

    def get_cluster_info(self) -> provision_common.ClusterInfo:
        return provision.get_cluster_info(self.provider_name,
                                          self.cluster_name)

    def get_command_runners(
            self,
            cluster_info: Optional[provision_common.ClusterInfo] = None
    ) -> List[command_runner_lib.CommandRunner]:
        if cluster_info is None:
            cluster_info = self.get_cluster_info()
        return provision.get_command_runners(self.provider_name, cluster_info)

    def cache_ips(self,
                  cluster_info: provision_common.ClusterInfo) -> None:
        self.stable_internal_external_ips = [
            (inst.internal_ip, inst.external_ip or inst.internal_ip)
            for inst in cluster_info.instances
        ]

    def external_ips(self) -> Optional[List[str]]:
        if self.stable_internal_external_ips is None:
            return None
        return [pair[1] for pair in self.stable_internal_external_ips]

    def __repr__(self) -> str:
        return (f'<SliceResourceHandle {self.cluster_name} '
                f'{self.launched_resources!r} hosts={self.num_hosts}>')


class RetryingProvisioner:
    """Failover loop: launchable × region × zone, with blocklist + re-opt."""

    def __init__(self, requested_task: 'task_lib.Task',
                 cluster_name: str) -> None:
        self._task = requested_task
        self._cluster_name = cluster_name
        self._blocked: List[Resources] = []
        self._failover_history: List[Exception] = []

    def provision_with_retries(
        self, to_provision: Resources
    ) -> Tuple[provision_common.ProvisionRecord, Resources]:
        """Try the chosen launchable; fail over across zones/regions/
        candidates until something provisions (parity reference :1934)."""
        journal = events_lib.cluster_journal(self._cluster_name)
        candidate = to_provision
        while True:
            result = self._try_candidate(candidate)
            if result is not None:
                return result
            self._blocked.append(candidate)
            try:
                launchables = optimizer_lib.Optimizer.enumerate_launchables(
                    self._task, blocked_resources=self._blocked)
            except exceptions.ResourcesUnavailableError as e:
                journal.append(
                    'provision_exhausted',
                    attempts=len(self._failover_history),
                    history=[type(x).__name__
                             for x in self._failover_history])
                raise exceptions.ResourcesUnavailableError(
                    f'Failed to provision {self._cluster_name} on all '
                    f'feasible resources. Attempts: '
                    f'{[str(x) for x in self._failover_history]}',
                    failover_history=self._failover_history) from e
            candidate = launchables[0][0]
            journal.append('provision_failover_candidate',
                           candidate=repr(candidate))
            logger.info(f'Failing over to next candidate: {candidate!r}')

    def _try_candidate(
        self, resources: Resources
    ) -> Optional[Tuple[provision_common.ProvisionRecord, Resources]]:
        cloud = resources.cloud
        assert cloud is not None, resources
        journal = events_lib.cluster_journal(self._cluster_name)
        cloud_name = str(getattr(cloud, 'PROVISIONER', cloud))
        for region, zones in cloud.zones_provision_loop(
                resources, region=resources.region):
            zone_names = [z.name for z in (zones or [])]
            if resources.zone is not None:
                zone_names = [z for z in zone_names if z == resources.zone]
                if not zone_names:
                    continue
            for zone_name in (zone_names or [None]):
                attempt = resources.copy(region=region.name, zone=zone_name)
                events_lib.provision_attempts().labels(
                    cloud=cloud_name).inc()
                journal.append('provision_attempt_start',
                               cloud=cloud_name, region=region.name,
                               zone=zone_name or '-')
                t0 = time.monotonic()
                try:
                    # Chaos site: a ProvisionError here is
                    # indistinguishable from a zone stockout, driving
                    # the real failover machinery below.
                    chaos_injector.inject('provision.create',
                                          cluster=self._cluster_name,
                                          cloud=cloud_name,
                                          region=region.name,
                                          zone=zone_name or '-')
                    record = self._provision_once(cloud, attempt, region,
                                                  zone_name)
                    journal.append(
                        'provision_attempt_end', status='ok',
                        cloud=cloud_name, region=region.name,
                        zone=zone_name or '-',
                        duration_s=round(time.monotonic() - t0, 6))
                    return record, attempt
                except (exceptions.ProvisionError,
                        exceptions.ResourcesUnavailableError) as e:
                    reason = type(e).__name__
                    journal.append(
                        'provision_attempt_end', status='fail',
                        cloud=cloud_name, region=region.name,
                        zone=zone_name or '-', reason=reason,
                        error=str(e)[:500],
                        duration_s=round(time.monotonic() - t0, 6))
                    events_lib.provision_failovers().labels(
                        reason=reason).inc()
                    logger.warning(
                        f'Provision attempt failed in {region.name}/'
                        f'{zone_name}: {e}')
                    self._failover_history.append(e)
                    continue
        return None

    def _provision_once(
            self, cloud: cloud_lib.Cloud, resources: Resources,
            region: cloud_lib.Region,
            zone_name: Optional[str]) -> provision_common.ProvisionRecord:
        zones = ([cloud_lib.Zone(zone_name, region.name)]
                 if zone_name else region.zones)
        deploy_vars = cloud.make_deploy_resources_variables(
            resources, self._cluster_name, region, zones)
        config = provision_common.ProvisionConfig(
            provider_name=cloud.PROVISIONER,
            cluster_name=self._cluster_name,
            region=region.name,
            zones=[z.name for z in zones],
            deploy_vars=deploy_vars,
            count=self._task.num_nodes,
            ports_to_open=resources.ports or [],
        )
        global_user_state.add_or_update_cluster(
            self._cluster_name,
            SliceResourceHandle(self._cluster_name, cloud.PROVISIONER,
                                resources, self._task.num_nodes),
            requested_resources=set(self._task.resources),
            ready=False)
        record = provisioner_lib.bulk_provision(config)
        if record.waiting:
            global_user_state.set_cluster_status(
                self._cluster_name, status_lib.ClusterStatus.WAITING)
            timeout_minutes = config_lib.get_nested(
                ('tpu', 'queued_timeout_minutes'),
                _QUEUED_CAPACITY_TIMEOUT_MINUTES_DEFAULT)
            granted = provisioner_lib.wait_for_queued_capacity(
                cloud.PROVISIONER, self._cluster_name,
                timeout=timeout_minutes * 60)
            if not granted:
                provisioner_lib.teardown_cluster(cloud.PROVISIONER,
                                                 self._cluster_name,
                                                 terminate=True)
                raise exceptions.ProvisionError(
                    f'Queued capacity not granted within '
                    f'{timeout_minutes} minutes.')
            provision.wait_instances(cloud.PROVISIONER, self._cluster_name)
        return record


class SliceBackend(backend_lib.Backend[SliceResourceHandle]):
    """The default backend."""

    NAME = 'slice'

    def __init__(self) -> None:
        self._optimize_target = optimizer_lib.OptimizeTarget.COST
        self._requested_features: set = set()

    def register_info(self, **kwargs: Any) -> None:
        self._optimize_target = kwargs.get('minimize_target',
                                           self._optimize_target)
        self._requested_features = kwargs.get('requested_features',
                                              self._requested_features)

    # ----------------------------------------------------------- provision

    def check_existing_cluster(
            self, cluster_name: str, task: 'task_lib.Task',
            acquire_lock: bool = True) -> Optional[SliceResourceHandle]:
        """Reuse an UP cluster if it satisfies the request.

        Parity: reference `_check_existing_cluster` (:4280).
        """
        record = global_user_state.get_cluster_from_name(cluster_name)
        if record is None or record['handle'] is None:
            return None
        handle: SliceResourceHandle = record['handle']
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        status = backend_utils.refresh_cluster_status(
            cluster_name, acquire_lock=acquire_lock)
        if status is None:
            return None
        if status != status_lib.ClusterStatus.UP:
            raise exceptions.ClusterNotUpError(
                f'Cluster {cluster_name} exists but is {status.value}; '
                f'run start first or pick a new name.',
                cluster_status=status, handle=handle)
        for requested in task.resources:
            if requested.less_demanding_than(handle.launched_resources):
                return handle
        raise exceptions.ResourcesMismatchError(
            f'Cluster {cluster_name} ({handle.launched_resources!r}) does '
            f'not satisfy the requested resources '
            f'({[str(r) for r in task.resources]}).')

    def _resync_runtime_if_upgraded(
            self, cluster_name: str,
            handle: SliceResourceHandle) -> None:
        """A reused cluster whose runtime predates this client gets the
        app tree re-shipped and the handle restamped — `sky launch` on
        the same name IS the upgrade path the skew check's error
        message promises (reference re-runs runtime setup on every
        launch; we pay the cost only on version change)."""
        import skypilot_tpu  # pylint: disable=import-outside-toplevel
        local_version = getattr(skypilot_tpu, '__version__', None)
        remote_version = getattr(handle, 'launched_runtime_version', None)
        if local_version is None or remote_version == local_version:
            return
        logger.info(
            f'Cluster {cluster_name} runtime is {remote_version}; '
            f'client is {local_version} — re-shipping the runtime.')
        cloud = handle.launched_resources.cloud
        provisioner_lib.post_provision_runtime_setup(
            handle.provider_name, cluster_name,
            credential_files=(cloud.get_credential_file_mounts()
                              if cloud is not None else None))
        handle.launched_runtime_version = local_version
        # requested_resources=None: restamping must not rewrite the
        # provision-time request in cluster history.
        global_user_state.add_or_update_cluster(
            cluster_name, handle, requested_resources=None, ready=True,
            is_launch=False)

    def _provision(self, task: 'task_lib.Task',
                   to_provision: Optional[Resources], dryrun: bool,
                   stream_logs: bool, cluster_name: str,
                   retry_until_up: bool = False
                   ) -> Optional[SliceResourceHandle]:
        # Per-cluster lock: concurrent `launch`es on one name must not
        # race provision (parity: reference FileLock,
        # cloud_vm_ray_backend.py:2729-2731).
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        with backend_utils.cluster_file_lock(cluster_name):
            return self._provision_no_lock(task, to_provision, dryrun,
                                           stream_logs, cluster_name,
                                           retry_until_up)

    def _provision_no_lock(self, task: 'task_lib.Task',
                           to_provision: Optional[Resources], dryrun: bool,
                           stream_logs: bool, cluster_name: str,
                           retry_until_up: bool = False
                           ) -> Optional[SliceResourceHandle]:
        del stream_logs
        common_utils.check_cluster_name_is_valid(cluster_name)
        existing = self.check_existing_cluster(cluster_name, task,
                                               acquire_lock=False)
        if existing is not None:
            logger.info(f'Reusing existing cluster {cluster_name}.')
            if not dryrun:  # dryrun must stay side-effect free
                self._resync_runtime_if_upgraded(cluster_name, existing)
            return existing
        if to_provision is None:
            launchables = optimizer_lib.Optimizer.enumerate_launchables(task)
            to_provision = launchables[0][0]
        if dryrun:
            logger.info(f'Dryrun: would provision {to_provision!r} as '
                        f'{cluster_name}.')
            return None
        cloud = to_provision.cloud
        assert cloud is not None
        type(cloud).check_features_are_supported(to_provision,
                                                 self._requested_features)

        backoff = common_utils.Backoff(initial_backoff=10.0)
        while True:
            retrier = RetryingProvisioner(task, cluster_name)
            try:
                record, launched = retrier.provision_with_retries(to_provision)
                break
            except exceptions.ResourcesUnavailableError:
                global_user_state.remove_cluster(cluster_name, terminate=True)
                if not retry_until_up:
                    raise
                # current_backoff is a property; calling it was a
                # latent crash on every retry_until_up wait.
                sleep_s = backoff.current_backoff
                logger.info(
                    f'retry_until_up: all candidates exhausted; retrying in '
                    f'{sleep_s:.0f}s.')
                time.sleep(sleep_s)

        cluster_info = provisioner_lib.post_provision_runtime_setup(
            record.provider_name, cluster_name,
            credential_files=cloud.get_credential_file_mounts())
        handle = SliceResourceHandle(cluster_name, record.provider_name,
                                     launched, task.num_nodes)
        handle.cache_ips(cluster_info)
        global_user_state.add_or_update_cluster(
            cluster_name, handle, requested_resources=set(task.resources),
            ready=True)
        global_user_state.set_owner_identity_for_cluster(
            cluster_name, cloud.get_current_user_identity())
        # `ssh <cluster>` UX (reference backend_utils.py:399): write the
        # managed Host block ONLY for clusters actually reachable over
        # ssh (an ssh key was provisioned).  Local hosts are
        # directories; GKE pods are kubectl-exec — neither runs sshd.
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        if cluster_info.ssh_private_key:
            ips = handle.external_ips() or []
            backend_utils.SSHConfigHelper.add_cluster(
                cluster_name, ips, ssh_user=cluster_info.ssh_user,
                ssh_private_key=cluster_info.ssh_private_key)
        return handle

    # ---------------------------------------------------------------- sync

    def _sync_workdir(self, handle: SliceResourceHandle,
                      workdir: str) -> None:
        runners = handle.get_command_runners()

        def _one(runner: command_runner_lib.CommandRunner) -> None:
            runner.rsync(workdir, constants.SKY_REMOTE_WORKDIR, up=True,
                         stream_logs=False)

        subprocess_utils.run_in_parallel(_one, runners)
        logger.info(f'Synced workdir {workdir!r} to '
                    f'{len(runners)} host(s).')

    def _sync_file_mounts(self, handle: SliceResourceHandle,
                          all_file_mounts: Optional[Dict[str, str]],
                          storage_mounts: Optional[Dict[str, Any]]) -> None:
        # Bucket-URL file mounts ({dst: 'gs://...'}) are COPY-mode
        # storage mounts in disguise — route them through the storage
        # layer (parity: reference cloud_vm_ray_backend.py:4406 turns
        # URL sources into cloud-CLI downloads on the cluster).
        from skypilot_tpu.data import storage as storage_lib  # pylint: disable=import-outside-toplevel
        storage_mounts = dict(storage_mounts or {})
        rsync_mounts: Dict[str, str] = {}
        for dst, src in (all_file_mounts or {}).items():
            if src.startswith(storage_lib.BUCKET_URL_PREFIXES):
                storage_mounts.setdefault(
                    dst, storage_lib.Storage(
                        source=src, mode=storage_lib.StorageMode.COPY))
            else:
                rsync_mounts[dst] = src
        if rsync_mounts:
            runners = handle.get_command_runners()

            def _one(runner: command_runner_lib.CommandRunner) -> None:
                for dst, src in rsync_mounts.items():
                    parent = os.path.dirname(dst.rstrip('/'))
                    if parent and parent not in ('~', '/'):
                        runner.run(f'mkdir -p {parent}', stream_logs=False)
                    runner.rsync(os.path.expanduser(src), dst, up=True,
                                 stream_logs=False)

            subprocess_utils.run_in_parallel(_one, runners)
        if storage_mounts:
            from skypilot_tpu.data import storage_mounting  # pylint: disable=import-outside-toplevel
            storage_mounting.execute_storage_mounts(handle, storage_mounts)

    # --------------------------------------------------------------- setup

    def _setup(self, handle: SliceResourceHandle, task: 'task_lib.Task',
               detach_setup: bool = False) -> None:
        del detach_setup
        if task.setup is None:
            return
        runners = handle.get_command_runners()
        script = log_lib.make_task_bash_script(
            f'cd {constants.SKY_REMOTE_WORKDIR} 2>/dev/null; {task.setup}',
            task.envs)
        run_timestamp = common_utils.generate_run_id()
        log_dir = os.path.join(os.path.expanduser('~/sky_logs'),
                               run_timestamp)
        results = command_runner_lib.run_on_all(runners, script,
                                               log_dir=log_dir)
        failed = [i for i, rc in enumerate(results) if rc != 0]
        if failed:
            raise exceptions.CommandError(
                returncode=1,
                command=f'setup ({task.setup[:80]}...)',
                error_msg=f'Setup failed on host(s) {failed}; logs in '
                          f'{log_dir}.')
        logger.info(f'Setup completed on {len(runners)} host(s).')

    # ------------------------------------------------------------- execute

    def _job_env_contract(self, handle: SliceResourceHandle,
                          task: 'task_lib.Task',
                          job_id: int) -> Dict[str, str]:
        resources = handle.launched_resources
        spec = resources.tpu_spec
        task_id = common_utils.get_global_job_id(
            common_utils.generate_run_id(), handle.cluster_name, str(job_id))
        env = {
            constants.ENV_TASK_ID: task_id,
            constants.ENV_CLUSTER_NAME: handle.cluster_name,
            constants.ENV_JOB_ID: str(job_id),
        }
        if spec is not None:
            env.update({
                constants.ENV_ACCEL_TYPE: spec.name,
                constants.ENV_TOPOLOGY: spec.topology_str,
                constants.ENV_CHIPS_PER_HOST: str(spec.chips_per_host),
            })
        if task.checkpoint_dir is not None:
            env[constants.ENV_CHECKPOINT_DIR] = task.checkpoint_dir
        return env

    def _execute(self, handle: SliceResourceHandle, task: 'task_lib.Task',
                 detach_run: bool, dryrun: bool = False) -> Optional[int]:
        if dryrun:
            logger.info(f'Dryrun: would execute {task!r} on '
                        f'{handle.cluster_name}.')
            return None
        if task.run is None:
            logger.info('Task has no run command; provisioning only.')
            return None
        cluster_info = handle.get_cluster_info()
        runners = handle.get_command_runners(cluster_info)
        head = runners[0]
        run_timestamp = common_utils.generate_run_id()

        resources_str = repr(handle.launched_resources)
        code = job_lib.JobLibCodeGen.add_job(task.name,
                                             job_lib.get_current_username(),
                                             run_timestamp, resources_str)
        rc, stdout, stderr = head.run(code, require_outputs=True,
                                      stream_logs=False)
        subprocess_utils.handle_returncode(rc, code,
                                           'Failed to register job.',
                                           stderr)
        job_id = job_lib.parse_job_id(stdout)

        run_cmd = task.run
        if callable(run_cmd):
            ips = cluster_info.get_feasible_ips()
            run_cmd = run_cmd(0, ips)
            if run_cmd is None:
                logger.info('Run generator returned None; nothing to do.')
                return job_id
        spec_dict = {
            'provider': handle.provider_name,
            'cluster_name': handle.cluster_name,
            'run_cmd': f'cd {constants.SKY_REMOTE_WORKDIR} 2>/dev/null; '
                       f'{run_cmd}',
            'envs': task.envs,
            'env_contract': self._job_env_contract(handle, task, job_id),
            'log_dir': os.path.join(constants.SKY_LOGS_DIRECTORY,
                                    run_timestamp),
            # LIVE host count, not the handle's launch-time view: after
            # an elastic shrink the gang must size itself to the hosts
            # that actually exist.
            'num_hosts': cluster_info.num_hosts,
            'hosts_per_slice':
                (handle.launched_resources.tpu_spec.num_hosts
                 if handle.launched_resources.tpu_spec else 1),
        }
        with tempfile.NamedTemporaryFile('w', suffix='.json',
                                         delete=False) as fp:
            json.dump(spec_dict, fp)
            local_spec = fp.name
        try:
            head.run(f'mkdir -p ~/.skytpu/jobs/{job_id}', stream_logs=False)
            head.rsync(local_spec, f'~/.skytpu/jobs/{job_id}/spec.json',
                       up=True, stream_logs=False)
        finally:
            os.remove(local_spec)

        supervisor_cmd = (
            f'mkdir -p {spec_dict["log_dir"]} && '
            f'PYTHONPATH={constants.SKY_REMOTE_APP_DIR}:$PYTHONPATH '
            f'{constants.SKY_PYTHON_CMD} -u -m '
            f'skypilot_tpu.backends.gang_supervisor --job-id {job_id} '
            f'>> {spec_dict["log_dir"]}/run.log 2>&1')
        code = job_lib.JobLibCodeGen.queue_job(job_id, supervisor_cmd)
        rc, _, stderr = head.run(code, require_outputs=True,
                                 stream_logs=False)
        subprocess_utils.handle_returncode(rc, code, 'Failed to queue job.',
                                           stderr)
        logger.info(f'Job {job_id} submitted on {handle.cluster_name} '
                    f'({cluster_info.num_hosts} host(s)).')
        if not detach_run:
            self.tail_logs(handle, job_id)
        return job_id

    def _post_execute(self, handle: SliceResourceHandle, down: bool) -> None:
        del handle, down

    # ---------------------------------------------------------------- logs

    def tail_logs(self, handle: SliceResourceHandle,
                  job_id: Optional[int], follow: bool = True,
                  tail: int = 0) -> int:
        head = handle.get_command_runners()[0]
        code = job_lib.JobLibCodeGen.tail_logs(job_id, follow=follow,
                                               tail=tail)
        rc = head.run(code, stream_logs=True)
        return rc if isinstance(rc, int) else rc[0]

    def sync_down_logs(self, handle: SliceResourceHandle,
                       job_id: Optional[int], local_dir: str) -> str:
        """Download a job's log directory from the head host."""
        head = handle.get_command_runners()[0]
        code = job_lib.JobLibCodeGen.get_log_dir(job_id)
        rc, stdout, stderr = head.run(code, require_outputs=True,
                                      stream_logs=False)
        subprocess_utils.handle_returncode(rc, code, 'Failed to resolve log '
                                           'dir.', stderr)
        remote_dir = job_lib.parse_tagged_json(stdout, 'LOG_DIR:')
        if remote_dir is None:
            raise exceptions.JobError(f'Job {job_id} has no logs.')
        target = os.path.join(os.path.expanduser(local_dir),
                              os.path.basename(remote_dir.rstrip('/')))
        os.makedirs(target, exist_ok=True)
        head.rsync(remote_dir, target, up=False, stream_logs=False)
        return target

    # ----------------------------------------------------------- job queue

    def get_job_queue(self, handle: SliceResourceHandle,
                      all_jobs: bool = True) -> List[Dict[str, Any]]:
        head = handle.get_command_runners()[0]
        code = job_lib.JobLibCodeGen.get_job_queue(all_jobs)
        rc, stdout, stderr = head.run(code, require_outputs=True,
                                      stream_logs=False)
        subprocess_utils.handle_returncode(rc, code,
                                           'Failed to fetch job queue.',
                                           stderr)
        return job_lib.parse_tagged_json(stdout, 'JOBS:')

    def cancel_jobs(self, handle: SliceResourceHandle,
                    job_ids: Optional[List[int]],
                    cancel_all: bool = False) -> List[int]:
        head = handle.get_command_runners()[0]
        code = job_lib.JobLibCodeGen.cancel_jobs(job_ids, cancel_all)
        rc, stdout, stderr = head.run(code, require_outputs=True,
                                      stream_logs=False)
        subprocess_utils.handle_returncode(rc, code, 'Failed to cancel.',
                                           stderr)
        return job_lib.parse_tagged_json(stdout, 'CANCELLED:')

    def get_job_status(
            self, handle: SliceResourceHandle,
            job_ids: Optional[List[int]] = None
    ) -> Dict[str, Optional[str]]:
        head = handle.get_command_runners()[0]
        code = job_lib.JobLibCodeGen.get_job_status(job_ids)
        rc, stdout, stderr = head.run(code, require_outputs=True,
                                      stream_logs=False)
        subprocess_utils.handle_returncode(rc, code,
                                           'Failed to fetch job status.',
                                           stderr)
        return job_lib.parse_tagged_json(stdout, 'STATUS:')

    # ------------------------------------------------------------ autostop

    def set_autostop(self, handle: SliceResourceHandle, idle_minutes: int,
                     down: bool = False) -> None:
        head = handle.get_command_runners()[0]
        code = autostop_lib_codegen(idle_minutes, down, handle.provider_name,
                                    handle.cluster_name)
        rc, _, stderr = head.run(code, require_outputs=True,
                                 stream_logs=False)
        subprocess_utils.handle_returncode(rc, code,
                                           'Failed to set autostop.', stderr)
        global_user_state.set_cluster_autostop_value(handle.cluster_name,
                                                     idle_minutes, down)

    # ------------------------------------------------------------ teardown

    def _teardown(self, handle: SliceResourceHandle, terminate: bool,
                  purge: bool = False) -> None:
        spec = handle.launched_resources.tpu_spec
        if not terminate and spec is not None and spec.is_pod:
            raise exceptions.NotSupportedError(
                f'Multi-host TPU slice {handle.cluster_name} cannot be '
                'stopped; use down/terminate.')
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        with backend_utils.cluster_file_lock(handle.cluster_name):
            try:
                provisioner_lib.teardown_cluster(handle.provider_name,
                                                 handle.cluster_name,
                                                 terminate)
            except Exception:  # pylint: disable=broad-except
                if not purge:
                    raise
                logger.warning(f'Purge: ignoring teardown failure of '
                               f'{handle.cluster_name}.')
            global_user_state.remove_cluster(handle.cluster_name,
                                             terminate=terminate)
            backend_utils.SSHConfigHelper.remove_cluster(
                handle.cluster_name)

    def run_on_head(self, handle: SliceResourceHandle, cmd: str,
                    **kwargs: Any) -> Any:
        """Arbitrary command on the head host (parity reference :4204)."""
        head = handle.get_command_runners()[0]
        return head.run(cmd, **kwargs)


def autostop_lib_codegen(idle_minutes: int, down: bool, provider_name: str,
                         cluster_name: str) -> str:
    """Head-side autostop config write, shipped like all JobLib codegens."""
    python = constants.SKY_PYTHON_CMD
    app_dir = constants.SKY_REMOTE_APP_DIR
    body = ('from skypilot_tpu.skylet import autostop_lib; '
            f'autostop_lib.set_autostop({idle_minutes}, {down}, '
            f'{provider_name!r}, {cluster_name!r})')
    import shlex  # pylint: disable=import-outside-toplevel
    return (f'PYTHONPATH={app_dir}:$PYTHONPATH {python} -u -c '
            f'{shlex.quote(body)}')
