"""Backend interface: provision → sync → setup → execute → teardown.

Parity: /root/reference/sky/backends/backend.py:30-170 (`Backend` ABC +
`ResourceHandle`), with the same timeline instrumentation points.
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Generic, Optional, TypeVar

from skypilot_tpu.utils import timeline

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib


class ResourceHandle:
    """Opaque, picklable pointer to launched capacity."""

    def get_cluster_name(self) -> str:
        raise NotImplementedError


_HandleT = TypeVar('_HandleT', bound=ResourceHandle)


class Backend(Generic[_HandleT]):
    """Abstract orchestration backend."""

    NAME = 'backend'

    # --- public API (timeline-instrumented), parity backend.py:45-125 ---

    @timeline.event
    def provision(self,
                  task: 'task_lib.Task',
                  to_provision: Optional['resources_lib.Resources'],
                  dryrun: bool,
                  stream_logs: bool,
                  cluster_name: str,
                  retry_until_up: bool = False) -> Optional[_HandleT]:
        return self._provision(task, to_provision, dryrun, stream_logs,
                               cluster_name, retry_until_up)

    @timeline.event
    def sync_workdir(self, handle: _HandleT, workdir: str) -> None:
        return self._sync_workdir(handle, workdir)

    @timeline.event
    def sync_file_mounts(self, handle: _HandleT,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        return self._sync_file_mounts(handle, all_file_mounts, storage_mounts)

    @timeline.event
    def setup(self, handle: _HandleT, task: 'task_lib.Task',
              detach_setup: bool = False) -> None:
        return self._setup(handle, task, detach_setup)

    @timeline.event
    def execute(self, handle: _HandleT, task: 'task_lib.Task',
                detach_run: bool, dryrun: bool = False) -> Optional[int]:
        return self._execute(handle, task, detach_run, dryrun)

    @timeline.event
    def post_execute(self, handle: _HandleT, down: bool) -> None:
        return self._post_execute(handle, down)

    @timeline.event
    def teardown(self, handle: _HandleT, terminate: bool,
                 purge: bool = False) -> None:
        return self._teardown(handle, terminate, purge)

    def register_info(self, **kwargs: Any) -> None:
        """Inject runtime info (optimize target, requested features...)."""
        del kwargs

    # --- subclass hooks ---

    def _provision(self, task, to_provision, dryrun, stream_logs,
                   cluster_name, retry_until_up):
        raise NotImplementedError

    def _sync_workdir(self, handle, workdir):
        raise NotImplementedError

    def _sync_file_mounts(self, handle, all_file_mounts, storage_mounts):
        raise NotImplementedError

    def _setup(self, handle, task, detach_setup):
        raise NotImplementedError

    def _execute(self, handle, task, detach_run, dryrun):
        raise NotImplementedError

    def _post_execute(self, handle, down):
        raise NotImplementedError

    def _teardown(self, handle, terminate, purge):
        raise NotImplementedError
