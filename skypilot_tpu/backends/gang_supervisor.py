"""Gang supervisor: runs ON the head host, drives one job across all hosts.

This replaces the reference's generated Ray driver program (`RayCodeGen`,
/root/reference/sky/backends/cloud_vm_ray_backend.py:209-686): where the
reference builds a placement group with STRICT_SPREAD and launches one Ray
task per node, a TPU slice *is already a gang* — membership and spread are
fixed by the hardware topology — so the supervisor simply fans the task
command out to every host over command runners, multiplexes per-rank logs,
fans failures in (`get_or_fail` semantics, reference :294-328), and records
the final job status in the head's job queue.

Invoked by the FIFO scheduler as `python -m
skypilot_tpu.backends.gang_supervisor --job-id N`; reads the job spec the
client wrote to ``~/.skytpu/jobs/<job_id>/spec.json``:

    {
      "provider": "local" | "gcp" | ...,
      "cluster_name": ...,
      "run_cmd": "...",                  # user task command
      "envs": {...},                     # user-declared env vars
      "env_contract": {...},             # TPU job contract (shared part)
      "log_dir": "~/sky_logs/<ts>",
      "num_hosts": N, "hosts_per_slice": H
    }

Exit status: 0 iff every rank exited 0. Any rank failing cancels the
remaining ranks (all-or-nothing, like a real slice failure).
"""
from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import job_lib
from skypilot_tpu.skylet import log_lib

logger = sky_logging.init_logger(__name__)


def _journal(job_id: Optional[int]) -> Optional[events_lib.EventJournal]:
    return (events_lib.cluster_job_journal(job_id)
            if job_id is not None else None)


def _spec_path(job_id: int) -> str:
    return os.path.expanduser(f'~/.skytpu/jobs/{job_id}/spec.json')


def load_spec(job_id: int) -> Dict[str, Any]:
    with open(_spec_path(job_id), encoding='utf-8') as f:
        return json.load(f)


def _rank_env(spec: Dict[str, Any], rank: int,
              host_ips: List[str]) -> Dict[str, str]:
    hosts_per_slice = int(spec.get('hosts_per_slice') or 1)
    num_hosts = len(host_ips)
    env = dict(spec.get('env_contract') or {})
    env.update({
        constants.ENV_HOST_RANK: str(rank),
        constants.ENV_HOST_IPS: '\n'.join(host_ips),
        constants.ENV_NUM_HOSTS: str(num_hosts),
        constants.ENV_SLICE_ID: str(rank // hosts_per_slice),
        constants.ENV_NUM_SLICES: str(max(1, num_hosts // hosts_per_slice)),
        constants.ENV_COORDINATOR_ADDRESS:
            f'{host_ips[0]}:{constants.JAX_COORDINATOR_PORT}',
    })
    # TPU runtime worker identity (consumed by libtpu on multi-host slices).
    env['TPU_WORKER_ID'] = str(rank % hosts_per_slice)
    env['TPU_WORKER_HOSTNAMES'] = ','.join(
        host_ips[(rank // hosts_per_slice) * hosts_per_slice:
                 (rank // hosts_per_slice + 1) * hosts_per_slice])
    for legacy, ours in constants.LEGACY_ENV_ALIASES.items():
        if ours in env:
            env[legacy] = env[ours]
    env.update(spec.get('envs') or {})
    return env


def run_gang(job_id: int, spec: Dict[str, Any]) -> int:
    provider = spec['provider']
    cluster_name = spec['cluster_name']
    cluster_info = provision.get_cluster_info(provider, cluster_name)
    runners = provision.get_command_runners(provider, cluster_info)
    host_ips = cluster_info.get_feasible_ips()
    log_dir = os.path.expanduser(spec['log_dir'])
    os.makedirs(os.path.join(log_dir, 'tasks'), exist_ok=True)

    job_lib.set_status(job_id, job_lib.JobStatus.RUNNING)
    run_cmd = spec['run_cmd']

    journal = _journal(job_id)
    if journal is not None:
        # rank -> host identity, so a post-mortem (and the elastic
        # recovery path) can tell WHICH host a dead rank lived on —
        # the report the ELASTIC strategy's survivor query confirms.
        hosts = {str(i): inst.instance_id
                 for i, inst in enumerate(
                     getattr(cluster_info, 'instances', None) or [])}
        journal.append('gang_start', job_id=job_id,
                       cluster=cluster_name, num_ranks=len(runners),
                       hosts=hosts)
    events_lib.gang_ranks_gauge().set(len(runners))

    try:
        returncodes = _run_gang_native(spec, runners, host_ips, log_dir,
                                       run_cmd, job_id=job_id)
        if returncodes is None:
            returncodes = _run_gang_python(runners, spec, host_ips,
                                           log_dir, run_cmd,
                                           job_id=job_id)
    except BaseException:
        # The opened gang lifecycle must terminate even when the
        # supervisor itself dies (journal-replay invariants would
        # otherwise read a crash here as a gang that never finished).
        if journal is not None:
            journal.append('gang_end', job_id=job_id, status='error',
                           returncodes={})
        raise

    ok = bool(returncodes) and all(rc == 0
                                   for rc in returncodes.values())
    status = (job_lib.JobStatus.SUCCEEDED if ok else job_lib.JobStatus.FAILED)
    job_lib.set_status(job_id, status)
    summary = {str(r): rc for r, rc in sorted(returncodes.items())}
    for rank, rc in sorted(returncodes.items()):
        events_lib.gang_rank_exits().labels(code=str(rc)).inc()
        if journal is not None:
            journal.append('rank_exit', job_id=job_id, rank=rank,
                           returncode=rc)
    if journal is not None:
        journal.append('gang_end', job_id=job_id,
                       status='ok' if ok else 'fail',
                       returncodes=summary)
    logger.info(f'[job {job_id}] gang finished: {json.dumps(summary)}')
    return 0 if ok else 1


def _run_gang_native(spec, runners, host_ips, log_dir, run_cmd,
                     job_id=None):
    """Supervise the gang with the C++ fan-in (one child per rank,
    line-multiplexed logs, fail-fast kill).  None → fall back."""
    from skypilot_tpu import native  # pylint: disable=import-outside-toplevel
    # Per-rank fault injection lives in the python supervisor's exec
    # path; an armed gang fault must not be silently bypassed by the
    # C++ fan-in.
    if chaos_injector.site_armed('gang.rank_exec') or \
            chaos_injector.site_armed('runner.exec'):
        return None
    binary = native.ensure_fanin_built()
    if binary is None:
        return None
    gang_tag = os.path.basename(log_dir.rstrip('/'))
    journal = _journal(job_id)
    argvs, log_paths, pidfiles = [], [], []
    for rank, runner in enumerate(runners):
        env = _rank_env(spec, rank, host_ips)
        pidfile = f'~/.skytpu/gang/{gang_tag}-rank{rank}.pid'
        exports = log_lib.make_task_bash_script(run_cmd, env,
                                                pidfile=pidfile)
        argv = runner.spawn_spec(exports)
        if argv is None:
            return None
        argvs.append(argv)
        pidfiles.append(pidfile)
        log_paths.append(os.path.join(log_dir, 'tasks',
                                      f'rank-{rank}.log'))
    spec_path = os.path.join(log_dir, 'fanin.spec')
    native.write_spec(spec_path, log_paths, argvs)
    if journal is not None:
        for rank in range(len(runners)):
            journal.append('rank_start', job_id=job_id, rank=rank,
                           supervisor='native')
    returncodes = native.run_fanin(binary, spec_path)
    if returncodes is not None and any(
            rc != 0 for rc in returncodes.values()):
        # The fan-in killed the LOCAL transports; over ssh/kubectl the
        # remote rank processes survive that, so sweep their process
        # trees via the pidfiles (ranks that exited cleanly removed
        # theirs — the sweep is a no-op there).
        _sweep_remote_kills(runners, pidfiles)
    return returncodes


def _sweep_remote_kills(runners, pidfiles) -> None:
    def _one(runner, pidfile):
        try:
            runner.run(log_lib.make_kill_tree_command(pidfile),
                       stream_logs=False)
        except Exception:  # pylint: disable=broad-except
            pass  # best-effort: the host may be the one that died

    threads = [
        threading.Thread(target=_one, args=(r, p), daemon=True)
        for r, p in zip(runners, pidfiles)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)


def _run_gang_python(runners, spec, host_ips, log_dir, run_cmd,
                     job_id=None):
    # Live transport processes by rank, so the first failure can kill
    # the survivors (fail-fast, matching the C++ fan-in and the
    # reference's get_or_fail :294-328) instead of leaving them blocked
    # in collectives until a timeout or manual cancel.
    procs_lock = threading.Lock()
    procs: Dict[int, Any] = {}
    aborting = threading.Event()
    journal = _journal(job_id)
    # Each rank records its remote PID so abort can kill the REMOTE
    # process tree: SIGTERMing the local ssh/kubectl client alone never
    # signals the far side (no tty; ControlMaster keeps the TCP up).
    gang_tag = os.path.basename(log_dir.rstrip('/'))

    def _pidfile(rank: int) -> str:
        return f'~/.skytpu/gang/{gang_tag}-rank{rank}.pid'

    def _one_rank(rank: int) -> int:
        runner = runners[rank]
        env = _rank_env(spec, rank, host_ips)
        exports = log_lib.make_task_bash_script(run_cmd, env,
                                                pidfile=_pidfile(rank))
        log_path = os.path.join(log_dir, 'tasks', f'rank-{rank}.log')
        if journal is not None:
            journal.append('rank_start', job_id=job_id, rank=rank,
                           supervisor='python')

        def _register(proc):
            with procs_lock:
                procs[rank] = proc
            if aborting.is_set():
                # Lost the race with the abort sweep: kill immediately.
                _kill_rank(runners[rank], _pidfile(rank), proc)

        def _on_retry(attempt, reason):
            # Expose the retry count to the flight recorder: a rank
            # that needed N transport attempts is a flaky host.
            if journal is not None:
                journal.append('runner_retry', job_id=job_id, rank=rank,
                               attempt=attempt, error=str(reason)[:500])

        # Chaos site: raising here kills exactly this rank (its
        # supervisor thread returns 255) and triggers the gang abort.
        chaos_injector.inject('gang.rank_exec', rank=rank,
                              job_id=job_id,
                              cluster=spec.get('cluster_name'))
        # stream_logs mirrors rank output to the supervisor's stdout, which
        # the scheduler redirects to run.log — what `sky logs` tails.
        return runner.run_with_retry(exports, log_path=log_path,
                                     stream_logs=True,
                                     on_spawn=_register,
                                     on_retry=_on_retry)

    def _abort_survivors(failed: int) -> None:
        aborting.set()
        with procs_lock:
            victims = [(r, p) for r, p in procs.items()
                       if r != failed and p.poll() is None]
        if not victims:
            return
        victim_ranks = sorted(r for r, _ in victims)
        logger.warning(f'[job {job_id}] rank {failed} failed: '
                       f'terminating ranks {victim_ranks}')
        t0 = time.monotonic()
        # Remote + local kills fan out in parallel; SIGKILL escalation
        # shares one deadline rather than 5s per rank.
        kill_threads = [
            threading.Thread(target=_kill_rank,
                             args=(runners[rank], _pidfile(rank), proc),
                             daemon=True)
            for rank, proc in victims
        ]
        for t in kill_threads:
            t.start()
        for t in kill_threads:
            t.join(timeout=30)
        abort_s = time.monotonic() - t0
        events_lib.gang_abort_hist().observe(abort_s)
        if journal is not None:
            journal.append('gang_abort', job_id=job_id,
                           failed_rank=failed, victims=victim_ranks,
                           duration_s=round(abort_s, 6))

    # Rank 0's log additionally mirrors to run.log for `sky logs` tailing.
    returncodes: Dict[int, int] = {}
    failed_rank = -1
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, len(runners))) as pool:
        futures = {
            pool.submit(_one_rank, rank): rank
            for rank in range(len(runners))
        }
        for fut in concurrent.futures.as_completed(futures):
            rank = futures[fut]
            if fut.cancelled():
                returncodes[rank] = 254  # never started: gang aborted
                continue
            try:
                rc = fut.result()
            except Exception as e:  # pylint: disable=broad-except
                logger.error(f'[job {job_id}] rank {rank} supervisor '
                             f'error: {e}')
                rc = 255
            returncodes[rank] = rc
            if rc != 0 and failed_rank < 0 and not aborting.is_set():
                failed_rank = rank
                # Fan-in failure (all-or-nothing slice semantics):
                # not-yet-started ranks are dropped; in-flight ranks are
                # SIGTERMed via their transport process groups.
                for fut_other in futures:
                    fut_other.cancel()
                _abort_survivors(rank)
    return returncodes


def _kill_rank(runner, pidfile: str, proc) -> None:
    """Kill one surviving rank: first its process tree ON THE HOST (via
    the pidfile the task script wrote — reaches through ssh/kubectl
    where killing the local client cannot), then the local transport
    process group (run_with_log starts each child in its own session,
    so pid == pgid), escalating to SIGKILL if it ignores SIGTERM."""
    try:
        runner.run(log_lib.make_kill_tree_command(pidfile),
                   stream_logs=False)
    except Exception:  # pylint: disable=broad-except
        pass  # best-effort: the host may be the one that died
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    args = parser.parse_args()
    spec = load_spec(args.job_id)
    log_dir = os.path.expanduser(spec['log_dir'])
    os.makedirs(log_dir, exist_ok=True)
    # The supervisor's own output is the job's driver log.
    sys.exit(run_gang(args.job_id, spec))


if __name__ == '__main__':
    main()
