"""Client-side sqlite state store.

Parity: /root/reference/sky/global_user_state.py:34-139 (tables: clusters with
pickled handle/status/autostop/owner-identity, cluster_history for cost
report, storage, enabled_clouds) — extended with a `queued_requests` notion
folded into cluster status (WAITING) for async TPU queued-resources.

DB path: $SKYTPU_HOME/state.db. All accessors open a short-lived connection;
sqlite's locking is the only concurrency control, as in the reference.
"""
from __future__ import annotations

import json
import os
import pickle
import sqlite3
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu import status_lib
from skypilot_tpu.utils import common_utils

_CREATE_TABLES = """\
CREATE TABLE IF NOT EXISTS clusters (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT,
    autostop INTEGER DEFAULT -1,
    to_down INTEGER DEFAULT 0,
    metadata TEXT DEFAULT '{}',
    owner TEXT DEFAULT null,
    cluster_hash TEXT DEFAULT null,
    storage_mounts_metadata BLOB DEFAULT null,
    cluster_ever_up INTEGER DEFAULT 0);
CREATE TABLE IF NOT EXISTS cluster_history (
    cluster_hash TEXT PRIMARY KEY,
    name TEXT,
    num_nodes INTEGER,
    requested_resources BLOB,
    launched_resources BLOB,
    usage_intervals BLOB);
CREATE TABLE IF NOT EXISTS storage (
    name TEXT PRIMARY KEY,
    launched_at INTEGER,
    handle BLOB,
    last_use TEXT,
    status TEXT);
CREATE TABLE IF NOT EXISTS enabled_clouds (
    name TEXT PRIMARY KEY);
"""


def _db_path() -> str:
    home = common_utils.ensure_dir(common_utils.skytpu_home())
    return os.path.join(home, 'state.db')


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(), timeout=10)
    conn.executescript(_CREATE_TABLES)
    return conn


# ---------------------------------------------------------------- clusters


def add_or_update_cluster(cluster_name: str,
                          cluster_handle: Any,
                          requested_resources: Optional[set],
                          ready: bool,
                          is_launch: bool = True) -> None:
    """Record a cluster in INIT (not ready) or UP (ready) state."""
    status = (status_lib.ClusterStatus.UP
              if ready else status_lib.ClusterStatus.INIT)
    handle = pickle.dumps(cluster_handle)
    now = int(time.time())
    cluster_hash = _get_hash_for_existing_cluster(cluster_name) or str(
        uuid.uuid4())
    usage_intervals = _get_cluster_usage_intervals(cluster_hash) or []
    if ready and (not usage_intervals or usage_intervals[-1][1] is not None):
        usage_intervals.append((now, None))
    with _conn() as conn:
        conn.execute(
            'INSERT INTO clusters (name, launched_at, handle, last_use, '
            'status, autostop, to_down, metadata, owner, cluster_hash, '
            'cluster_ever_up) '
            'VALUES (?, ?, ?, ?, ?, -1, 0, ?, null, ?, ?) '
            'ON CONFLICT(name) DO UPDATE SET '
            'handle=excluded.handle, status=excluded.status, '
            'launched_at=CASE WHEN ? THEN excluded.launched_at '
            '            ELSE clusters.launched_at END, '
            'last_use=excluded.last_use, '
            'cluster_ever_up=clusters.cluster_ever_up OR excluded.cluster_ever_up',
            (cluster_name, now, handle, _last_use(), status.value, '{}',
             cluster_hash, int(ready), int(is_launch)))
        if requested_resources is not None:
            launched = getattr(cluster_handle, 'launched_resources', None)
            num_nodes = getattr(cluster_handle, 'launched_nodes', None)
            conn.execute(
                'INSERT INTO cluster_history (cluster_hash, name, num_nodes, '
                'requested_resources, launched_resources, usage_intervals) '
                'VALUES (?, ?, ?, ?, ?, ?) '
                'ON CONFLICT(cluster_hash) DO UPDATE SET '
                'num_nodes=excluded.num_nodes, '
                'requested_resources=excluded.requested_resources, '
                'launched_resources=excluded.launched_resources, '
                'usage_intervals=excluded.usage_intervals',
                (cluster_hash, cluster_name, num_nodes,
                 pickle.dumps(requested_resources), pickle.dumps(launched),
                 pickle.dumps(usage_intervals)))


def _last_use() -> str:
    import sys  # pylint: disable=import-outside-toplevel
    return ' '.join([os.path.basename(sys.argv[0])] + sys.argv[1:])[:256]


def update_cluster_handle(cluster_name: str, cluster_handle: Any) -> None:
    with _conn() as conn:
        conn.execute('UPDATE clusters SET handle=? WHERE name=?',
                     (pickle.dumps(cluster_handle), cluster_name))


def set_cluster_status(cluster_name: str,
                       status: status_lib.ClusterStatus) -> None:
    with _conn() as conn:
        cur = conn.execute('UPDATE clusters SET status=? WHERE name=?',
                           (status.value, cluster_name))
        if cur.rowcount == 0:
            raise ValueError(f'Cluster {cluster_name} not found.')
    if status == status_lib.ClusterStatus.STOPPED:
        _close_usage_interval(cluster_name)


def set_cluster_autostop_value(cluster_name: str, idle_minutes: int,
                               to_down: bool) -> None:
    with _conn() as conn:
        conn.execute('UPDATE clusters SET autostop=?, to_down=? WHERE name=?',
                     (idle_minutes, int(to_down), cluster_name))


def get_cluster_from_name(cluster_name: str) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        row = conn.execute('SELECT * FROM clusters WHERE name=?',
                           (cluster_name,)).fetchone()
        if row is None:
            return None
        cols = [d[0] for d in conn.execute(
            'SELECT * FROM clusters LIMIT 0').description]
    return _row_to_record(dict(zip(cols, row)))


def _row_to_record(r: Dict[str, Any]) -> Dict[str, Any]:
    return {
        'name': r['name'],
        'launched_at': r['launched_at'],
        'handle': pickle.loads(r['handle']) if r['handle'] else None,
        'last_use': r['last_use'],
        'status': status_lib.ClusterStatus(r['status']),
        'autostop': r['autostop'],
        'to_down': bool(r['to_down']),
        'metadata': json.loads(r['metadata'] or '{}'),
        'owner': r['owner'],
        'cluster_hash': r['cluster_hash'],
        'cluster_ever_up': bool(r['cluster_ever_up']),
    }


def get_clusters() -> List[Dict[str, Any]]:
    with _conn() as conn:
        cols = [d[0] for d in conn.execute(
            'SELECT * FROM clusters LIMIT 0').description]
        rows = conn.execute(
            'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    return [_row_to_record(dict(zip(cols, r))) for r in rows]


def get_glob_cluster_names(glob_pattern: str) -> List[str]:
    with _conn() as conn:
        rows = conn.execute('SELECT name FROM clusters WHERE name GLOB ?',
                            (glob_pattern,)).fetchall()
    return [r[0] for r in rows]


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    _close_usage_interval(cluster_name)
    with _conn() as conn:
        if terminate:
            conn.execute('DELETE FROM clusters WHERE name=?', (cluster_name,))
        else:
            record = get_cluster_from_name(cluster_name)
            if record is None:
                return
            handle = record['handle']
            if handle is not None and hasattr(handle, 'stable_internal_external_ips'):
                handle.stable_internal_external_ips = None
            conn.execute(
                'UPDATE clusters SET handle=?, status=? WHERE name=?',
                (pickle.dumps(handle), status_lib.ClusterStatus.STOPPED.value,
                 cluster_name))


def set_owner_identity_for_cluster(cluster_name: str,
                                   owner_identity: Optional[List[str]]) -> None:
    if owner_identity is None:
        return
    with _conn() as conn:
        conn.execute('UPDATE clusters SET owner=? WHERE name=?',
                     (json.dumps(owner_identity), cluster_name))


def get_owner_identity_for_cluster(cluster_name: str) -> Optional[List[str]]:
    with _conn() as conn:
        row = conn.execute('SELECT owner FROM clusters WHERE name=?',
                           (cluster_name,)).fetchone()
    if row is None or row[0] is None:
        return None
    return json.loads(row[0])


def get_cluster_metadata(cluster_name: str) -> Optional[Dict[str, Any]]:
    rec = get_cluster_from_name(cluster_name)
    return rec['metadata'] if rec else None


def set_cluster_metadata(cluster_name: str, metadata: Dict[str, Any]) -> None:
    with _conn() as conn:
        conn.execute('UPDATE clusters SET metadata=? WHERE name=?',
                     (json.dumps(metadata), cluster_name))


def set_cluster_storage_mounts_metadata(cluster_name: str,
                                        metadata: Any) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE clusters SET storage_mounts_metadata=? WHERE name=?',
            (pickle.dumps(metadata), cluster_name))


def get_cluster_storage_mounts_metadata(cluster_name: str) -> Any:
    with _conn() as conn:
        row = conn.execute(
            'SELECT storage_mounts_metadata FROM clusters WHERE name=?',
            (cluster_name,)).fetchone()
    if row is None or row[0] is None:
        return None
    return pickle.loads(row[0])


# ------------------------------------------------------- usage / cost report


def _get_hash_for_existing_cluster(cluster_name: str) -> Optional[str]:
    with _conn() as conn:
        row = conn.execute('SELECT cluster_hash FROM clusters WHERE name=?',
                           (cluster_name,)).fetchone()
    return row[0] if row else None


def _get_cluster_usage_intervals(cluster_hash: Optional[str]):
    if cluster_hash is None:
        return None
    with _conn() as conn:
        row = conn.execute(
            'SELECT usage_intervals FROM cluster_history WHERE cluster_hash=?',
            (cluster_hash,)).fetchone()
    if row is None or row[0] is None:
        return None
    return pickle.loads(row[0])


def _close_usage_interval(cluster_name: str) -> None:
    cluster_hash = _get_hash_for_existing_cluster(cluster_name)
    intervals = _get_cluster_usage_intervals(cluster_hash)
    if not intervals or intervals[-1][1] is not None:
        return
    start, _ = intervals[-1]
    intervals[-1] = (start, int(time.time()))
    with _conn() as conn:
        conn.execute(
            'UPDATE cluster_history SET usage_intervals=? WHERE cluster_hash=?',
            (pickle.dumps(intervals), cluster_hash))


def get_cluster_duration(cluster_hash: str) -> int:
    intervals = _get_cluster_usage_intervals(cluster_hash) or []
    total = 0
    for start, end in intervals:
        if end is None:
            end = int(time.time())
        total += end - start
    return total


def get_clusters_from_history() -> List[Dict[str, Any]]:
    with _conn() as conn:
        rows = conn.execute(
            'SELECT ch.cluster_hash, ch.name, ch.num_nodes, '
            'ch.requested_resources, ch.launched_resources, '
            'ch.usage_intervals, c.status '
            'FROM cluster_history ch LEFT JOIN clusters c '
            'ON ch.cluster_hash = c.cluster_hash').fetchall()
    records = []
    for (cluster_hash, name, num_nodes, requested, launched, intervals,
         status) in rows:
        records.append({
            'name': name,
            'num_nodes': num_nodes,
            'requested_resources': pickle.loads(requested) if requested else None,
            'launched_resources': pickle.loads(launched) if launched else None,
            'duration': get_cluster_duration(cluster_hash),
            'status': status_lib.ClusterStatus(status) if status else None,
        })
    return records


# ----------------------------------------------------------------- storage


def add_or_update_storage(storage_name: str, storage_handle: Any,
                          storage_status: status_lib.StorageStatus) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO storage VALUES (?, ?, ?, ?, ?)',
            (storage_name, int(time.time()), pickle.dumps(storage_handle),
             _last_use(), storage_status.value))


def remove_storage(storage_name: str) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM storage WHERE name=?', (storage_name,))


def set_storage_status(storage_name: str,
                       storage_status: status_lib.StorageStatus) -> None:
    with _conn() as conn:
        cur = conn.execute('UPDATE storage SET status=? WHERE name=?',
                           (storage_status.value, storage_name))
        if cur.rowcount == 0:
            raise ValueError(f'Storage {storage_name} not found.')


def get_storage_status(
        storage_name: str) -> Optional[status_lib.StorageStatus]:
    with _conn() as conn:
        row = conn.execute('SELECT status FROM storage WHERE name=?',
                           (storage_name,)).fetchone()
    return status_lib.StorageStatus(row[0]) if row else None


def get_handle_from_storage_name(storage_name: str) -> Any:
    with _conn() as conn:
        row = conn.execute('SELECT handle FROM storage WHERE name=?',
                           (storage_name,)).fetchone()
    return pickle.loads(row[0]) if row and row[0] else None


def get_storage() -> List[Dict[str, Any]]:
    with _conn() as conn:
        rows = conn.execute('SELECT * FROM storage').fetchall()
    return [{
        'name': r[0],
        'launched_at': r[1],
        'handle': pickle.loads(r[2]) if r[2] else None,
        'last_use': r[3],
        'status': status_lib.StorageStatus(r[4]),
    } for r in rows]


# ------------------------------------------------------------ enabled infra


def set_enabled_clouds(enabled_clouds: List[str]) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM enabled_clouds')
        conn.executemany('INSERT INTO enabled_clouds VALUES (?)',
                         [(c,) for c in enabled_clouds])


def get_enabled_clouds() -> List[str]:
    with _conn() as conn:
        rows = conn.execute('SELECT name FROM enabled_clouds').fetchall()
    return [r[0] for r in rows]
