"""Imperative cluster/job operations backing the CLI.

Parity: /root/reference/sky/core.py:1-914 (status/start/stop/down/autostop/
queue/cancel/tail_logs/download_logs/job_status/cost_report).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu import status_lib
from skypilot_tpu.backends import backend_utils
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.provision import provisioner as provisioner_lib

logger = sky_logging.init_logger(__name__)


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records from local state (optionally cloud-reconciled).

    Each record carries 'last_launch' — the most recent launch's
    stage-runtime decomposition (usage_lib) — so time-to-first-step is
    inspectable per cluster (reference usage_lib.py:265 parity,
    surfaced locally instead of phoned home).
    """
    from skypilot_tpu import usage_lib  # pylint: disable=import-outside-toplevel
    records = backend_utils.get_clusters(refresh=refresh,
                                         cluster_names=cluster_names)
    launches = usage_lib.latest_launches()
    for record in records:
        record['last_launch'] = launches.get(record['name'])
    return records


def start(cluster_name: str,
          idle_minutes_to_autostop: Optional[int] = None,
          retry_until_up: bool = False) -> None:
    """Restart a STOPPED cluster (same provider/zone; no re-optimization)."""
    del retry_until_up
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle: slice_backend.SliceResourceHandle = record['handle']
    current = backend_utils.refresh_cluster_status(cluster_name)
    if current == status_lib.ClusterStatus.UP:
        logger.info(f'Cluster {cluster_name} is already UP.')
        return
    cloud = handle.launched_resources.cloud
    assert cloud is not None
    region = handle.launched_resources.region or ''
    zones = [handle.launched_resources.zone] if handle.launched_resources.zone else []
    from skypilot_tpu.provision import common as provision_common  # pylint: disable=import-outside-toplevel
    deploy_vars = cloud.make_deploy_resources_variables(
        handle.launched_resources, cluster_name,
        _region_obj(cloud, region), None)
    config = provision_common.ProvisionConfig(
        provider_name=handle.provider_name,
        cluster_name=cluster_name,
        region=region,
        zones=[z for z in zones if z],
        deploy_vars=deploy_vars,
        count=handle.launched_nodes,
    )
    provisioner_lib.bulk_provision(config)
    cluster_info = provisioner_lib.post_provision_runtime_setup(
        handle.provider_name, cluster_name,
        credential_files=cloud.get_credential_file_mounts())
    handle.cache_ips(cluster_info)
    # The runtime just re-shipped from THIS client: restamp so the
    # exec-time skew check agrees stop/start resyncs (the skew policy's
    # documented second healing path besides relaunch).
    import skypilot_tpu  # pylint: disable=import-outside-toplevel
    handle.launched_runtime_version = getattr(skypilot_tpu,
                                              '__version__', None)
    global_user_state.add_or_update_cluster(cluster_name, handle,
                                            requested_resources=None,
                                            ready=True, is_launch=False)
    if idle_minutes_to_autostop is not None:
        backend = slice_backend.SliceBackend()
        backend.set_autostop(handle, idle_minutes_to_autostop)


def _region_obj(cloud, region_name: str):
    from skypilot_tpu.clouds import cloud as cloud_lib  # pylint: disable=import-outside-toplevel
    del cloud
    return cloud_lib.Region(region_name)


def stop(cluster_name: str) -> None:
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle = record['handle']
    backend = slice_backend.SliceBackend()
    backend.teardown(handle, terminate=False)
    logger.info(f'Cluster {cluster_name} stopped.')


def down(cluster_name: str, purge: bool = False) -> None:
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle = record['handle']
    if handle is None:
        global_user_state.remove_cluster(cluster_name, terminate=True)
        return
    backend = slice_backend.SliceBackend()
    backend.teardown(handle, terminate=True, purge=purge)
    logger.info(f'Cluster {cluster_name} terminated.')


def autostop(cluster_name: str, idle_minutes: int,
             down_after: bool = False) -> None:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = slice_backend.SliceBackend()
    backend.set_autostop(handle, idle_minutes, down_after)


def queue(cluster_name: str,
          all_jobs: bool = True) -> List[Dict[str, Any]]:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = slice_backend.SliceBackend()
    return backend.get_job_queue(handle, all_jobs)


def cancel(cluster_name: str,
           job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = slice_backend.SliceBackend()
    return backend.cancel_jobs(handle, job_ids, cancel_all=all_jobs)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True, tail: int = 0) -> int:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = slice_backend.SliceBackend()
    return backend.tail_logs(handle, job_id, follow=follow, tail=tail)


def download_logs(cluster_name: str, job_id: Optional[int] = None,
                  local_dir: str = '~/sky_logs') -> str:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = slice_backend.SliceBackend()
    return backend.sync_down_logs(handle, job_id, local_dir)


def job_status(cluster_name: str,
               job_ids: Optional[List[int]] = None
               ) -> Dict[str, Optional[str]]:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = slice_backend.SliceBackend()
    return backend.get_job_status(handle, job_ids)


def cost_report() -> List[Dict[str, Any]]:
    """Accumulated cost per cluster from usage intervals.

    Parity: reference core.py cost_report (resources price × up-duration).
    """
    from skypilot_tpu import usage_lib  # pylint: disable=import-outside-toplevel
    records = global_user_state.get_clusters_from_history()
    launches = usage_lib.latest_launches()
    for record in records:
        launched = record.get('launched_resources')
        duration = record.get('duration', 0)
        cost = 0.0
        if launched is not None:
            try:
                cost = launched.get_cost(duration) * (record.get('num_nodes')
                                                      or 1)
            except Exception:  # pylint: disable=broad-except
                cost = 0.0
        record['total_cost'] = cost
        # Launch-overhead decomposition: cost is only half the story —
        # time-to-first-step is the north-star denominator.
        launch_rec = launches.get(record.get('name') or '')
        record['time_to_first_step'] = (
            launch_rec['time_to_first_step'] if launch_rec else None)
    return records


def queued_status(cluster_name: str) -> bool:
    """Poll an async (queued-resource) cluster once; True if granted."""
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle = record['handle']
    return provision.wait_capacity(handle.provider_name, cluster_name)


def endpoints(cluster_name: str,
              port: Optional[int] = None) -> Dict[int, str]:
    """Exposed `port -> host:port` endpoints of a cluster's head host.

    Parity: reference core.py:189 (endpoints). Ports come from the
    launched resources' `ports` request; the host is the head node's
    externally reachable IP.
    """
    handle = backend_utils.check_cluster_available(cluster_name)
    ips = handle.external_ips() or []
    if not ips:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} has no reachable IPs.')
    resources = getattr(handle, 'launched_resources', None)
    ports = list(getattr(resources, 'ports', None) or [])
    if not ports:
        # Reference parity: an UP cluster without a ports request is an
        # error, not an empty dict — the user asked for endpoints that
        # were never opened.
        raise ValueError(
            f'Cluster {cluster_name!r} has no open ports; request '
            "`resources.ports` at launch to expose endpoints.")
    if port is not None:
        if port not in ports:
            raise ValueError(
                f'Port {port} was not opened on {cluster_name!r} '
                f'(open ports: {ports}).')
        ports = [port]
    return {p: f'{ips[0]}:{p}' for p in ports}


def storage_ls() -> List[Dict[str, Any]]:
    """Storage records from local state.

    Parity: reference core.py:877 (storage_ls).
    """
    return global_user_state.get_storage()


def storage_delete(name: str) -> None:
    """Delete a storage object and its bucket(s).

    Parity: reference core.py:899 (storage_delete).
    """
    from skypilot_tpu.data import storage as storage_lib  # pylint: disable=import-outside-toplevel
    handle = global_user_state.get_handle_from_storage_name(name)
    if handle is None:
        raise exceptions.StorageError(f'Storage {name!r} not found.')
    storage = storage_lib.Storage(
        name=handle['name'], source=handle.get('source'),
        mode=storage_lib.StorageMode(handle.get('mode', 'MOUNT')))
    for stype in handle.get('store_types', []):
        storage.stores[storage_lib.StoreType(stype)] = (
            storage_lib._STORE_CLASSES[  # pylint: disable=protected-access
                storage_lib.StoreType(stype)](handle['name']))
    storage.delete()
