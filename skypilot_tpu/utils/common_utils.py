"""Small shared helpers: user/cluster naming, hashing, retries, validation.

Parity: /root/reference/sky/utils/common_utils.py (user hash, cluster-name
validation, backoff) — re-implemented minimally.
"""
from __future__ import annotations

import functools
import hashlib
import json
import os
import random
import re
import time
import uuid
from typing import Any, Callable, Dict, Optional

_USER_HASH_FILE_NAME = 'user_hash'
USER_HASH_LENGTH = 8

CLUSTER_NAME_VALID_REGEX = re.compile(r'^[a-zA-Z]([-_.a-zA-Z0-9]*[a-zA-Z0-9])?$')


def skytpu_home() -> str:
    """Root of all client-side state (overridable for hermetic tests)."""
    return os.path.expanduser(os.environ.get('SKYTPU_HOME', '~/.skytpu'))


def ensure_dir(path: str, mode: int = 0o777) -> str:
    os.makedirs(path, mode=mode, exist_ok=True)
    return path


def get_user_hash() -> str:
    """Stable per-user identifier, cached on disk."""
    env = os.environ.get('SKYTPU_USER_HASH')
    if env:
        return env[:USER_HASH_LENGTH]
    path = os.path.join(skytpu_home(), _USER_HASH_FILE_NAME)
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            h = f.read().strip()
        if h:
            return h[:USER_HASH_LENGTH]
    h = hashlib.md5(uuid.uuid4().bytes).hexdigest()[:USER_HASH_LENGTH]
    ensure_dir(skytpu_home())
    with open(path, 'w', encoding='utf-8') as f:
        f.write(h)
    return h


def get_user() -> str:
    return os.environ.get('USER', os.environ.get('LOGNAME', 'unknown'))


def check_cluster_name_is_valid(name: Optional[str]) -> None:
    from skypilot_tpu import exceptions  # pylint: disable=import-outside-toplevel
    if name is None:
        return
    if len(name) > 63 or CLUSTER_NAME_VALID_REGEX.match(name) is None:
        raise exceptions.InvalidClusterNameError(
            f'Cluster name {name!r} is invalid: must match '
            f'{CLUSTER_NAME_VALID_REGEX.pattern} and be <= 63 chars.')


def make_cluster_name_on_cloud(display_name: str,
                               max_length: int = 35) -> str:
    """Append the user hash so two users' clusters never collide on-cloud."""
    user_hash = get_user_hash()
    name = f'{display_name}-{user_hash}'
    if len(name) <= max_length:
        return name
    digest = hashlib.md5(display_name.encode()).hexdigest()[:4]
    prefix_len = max_length - len(user_hash) - len(digest) - 2
    return f'{display_name[:prefix_len]}-{digest}-{user_hash}'


def base36(n: int) -> str:
    chars = '0123456789abcdefghijklmnopqrstuvwxyz'
    if n == 0:
        return '0'
    out = []
    while n:
        n, r = divmod(n, 36)
        out.append(chars[r])
    return ''.join(reversed(out))


def get_global_job_id(job_timestamp: str, cluster_name: str,
                      job_id: str) -> str:
    return f'{job_timestamp}_{cluster_name}_id-{job_id}'


def generate_run_id() -> str:
    import datetime  # pylint: disable=import-outside-toplevel
    ts = datetime.datetime.now().strftime('%Y-%m-%d-%H-%M-%S-%f')
    return f'sky-{ts}-{uuid.uuid4().hex[:6]}'


class Backoff:
    """Exponential backoff with jitter."""

    MULTIPLIER = 1.6
    JITTER = 0.4

    def __init__(self, initial_backoff: float = 5.0,
                 max_backoff_factor: int = 5) -> None:
        self._initial = initial_backoff
        self._max = initial_backoff * (self.MULTIPLIER**max_backoff_factor)
        self._backoff = 0.0
        self._next = initial_backoff

    @property
    def current_backoff(self) -> float:
        """Advance and return the next backoff duration in seconds."""
        self._backoff = min(self._next, self._max)
        self._next = self._backoff * self.MULTIPLIER
        jitter = self._backoff * self.JITTER * (2 * random.random() - 1)
        return max(0.1, self._backoff + jitter)


def retry(fn: Optional[Callable] = None, *, max_retries: int = 3,
          initial_backoff: float = 1.0,
          exceptions_to_retry: tuple = (Exception,)) -> Callable:
    """Decorator: retry with exponential backoff."""

    def deco(func: Callable) -> Callable:

        @functools.wraps(func)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            backoff = Backoff(initial_backoff)
            for attempt in range(max_retries + 1):
                try:
                    return func(*args, **kwargs)
                except exceptions_to_retry:
                    if attempt == max_retries:
                        raise
                    time.sleep(backoff.current_backoff)
            raise AssertionError('unreachable')

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco


def read_yaml(path: str) -> Dict[str, Any]:
    import yaml  # pylint: disable=import-outside-toplevel
    with open(path, encoding='utf-8') as f:
        config = yaml.safe_load(f)
    return config if config is not None else {}


def read_yaml_all(path: str) -> list:
    import yaml  # pylint: disable=import-outside-toplevel
    with open(path, encoding='utf-8') as f:
        return [c for c in yaml.safe_load_all(f) if c is not None]


def dump_yaml(path: str, config: Any) -> None:
    with open(path, 'w', encoding='utf-8') as f:
        f.write(dump_yaml_str(config))


def dump_yaml_str(config: Any) -> str:
    import yaml  # pylint: disable=import-outside-toplevel

    class _Dumper(yaml.SafeDumper):
        pass

    _Dumper.add_representer(
        type(None),
        lambda d, _: d.represent_scalar('tag:yaml.org,2002:null', 'null'))
    if isinstance(config, list):
        return yaml.dump_all(config, Dumper=_Dumper, default_flow_style=False)
    return yaml.dump(config, Dumper=_Dumper, default_flow_style=False)


def format_exception(e: BaseException, use_bracket: bool = False) -> str:
    name = type(e).__name__
    if use_bracket:
        return f'[{name}] {e}'
    return f'{name}: {e}'


def json_dumps_compact(obj: Any) -> str:
    return json.dumps(obj, separators=(',', ':'), sort_keys=True)


def truncate_long_string(s: str, max_length: int = 35) -> str:
    if len(s) <= max_length:
        return s
    return s[:max_length - 3] + '...'


def fill_template(template_str: str, variables: Dict[str, Any]) -> str:
    import jinja2  # pylint: disable=import-outside-toplevel
    env = jinja2.Environment(undefined=jinja2.StrictUndefined,
                             trim_blocks=True,
                             lstrip_blocks=True)
    return env.from_string(template_str).render(**variables)


def validate_schema_keys(config: Dict[str, Any], allowed: set,
                         what: str) -> None:
    """Reject unknown keys in a YAML sub-config with a pointed error."""
    from skypilot_tpu import exceptions  # pylint: disable=import-outside-toplevel
    unknown = set(config) - allowed
    if unknown:
        raise exceptions.InvalidTaskError(
            f'Unknown key(s) in {what} config: {sorted(unknown)}; '
            f'allowed: {sorted(allowed)}')
