"""Helpers to normalize entrypoints (Task | Dag) into a Dag.

Parity: /root/reference/sky/utils/dag_utils.py:1-172.
"""
from __future__ import annotations

from typing import Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib


def convert_entrypoint_to_dag(
        entrypoint: Union[task_lib.Task, dag_lib.Dag]) -> dag_lib.Dag:
    if isinstance(entrypoint, dag_lib.Dag):
        return entrypoint
    if isinstance(entrypoint, task_lib.Task):
        dag = dag_lib.Dag(name=entrypoint.name)
        dag.add(entrypoint)
        return dag
    raise exceptions.InvalidTaskError(
        f'Entrypoint must be a Task or Dag, got {type(entrypoint)}.')


def load_chain_dag_from_yaml(yaml_path: str) -> dag_lib.Dag:
    """A YAML file with multiple documents is a chain DAG (managed jobs)."""
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    configs = [c for c in common_utils.read_yaml_all(yaml_path) if c]
    return load_chain_dag_from_configs(configs)


def load_chain_dag_from_configs(configs) -> dag_lib.Dag:
    """Chain DAG from already-parsed YAML documents (callers that have
    the docs in hand avoid re-reading the file)."""
    dag = dag_lib.Dag()
    # Reference convention: a first MAPPING document containing ONLY
    # `name:` names the pipeline; it is not a task.
    if (len(configs) > 1 and isinstance(configs[0], dict) and
            set(configs[0]) == {'name'}):
        dag.name = configs[0]['name']
        configs = configs[1:]
    prev = None
    for config in configs:
        task = task_lib.Task.from_yaml_config(config)
        dag.add(task)
        if prev is not None:
            dag.add_edge(prev, task)
        prev = task
    if dag.name is None and dag.tasks:
        dag.name = dag.tasks[0].name
    return dag

def dump_chain_dag_to_yaml(dag: dag_lib.Dag, yaml_path: str) -> None:
    """Serialize a chain DAG as a multi-document YAML (inverse of
    load_chain_dag_from_yaml).

    A name-only header document always leads, so the round trip
    preserves the DAG name AND a first task that happens to serialize
    to only `name:` can never be mistaken for the header on reload.

    An empty DAG dumps as an empty file — losing its name: a lone
    header document would reload as a task config (the header rule
    needs >1 documents, matching the reference convention that a
    single-document YAML is a task) and crash Task.from_yaml_config.
    No production path dumps an empty DAG; the round trip just must
    not crash.
    """
    import yaml  # pylint: disable=import-outside-toplevel
    if not dag.tasks:
        with open(yaml_path, 'w', encoding='utf-8') as f:
            f.write('')
        return
    configs = [{'name': dag.name or dag.tasks[0].name}]
    configs += [task.to_yaml_config() for task in dag.tasks]
    with open(yaml_path, 'w', encoding='utf-8') as f:
        yaml.safe_dump_all(configs, f, default_flow_style=False,
                           sort_keys=False)
