"""JSON schemas for task YAML, resources, storage, and user config.

Parity: /root/reference/sky/utils/schemas.py (941 LoC of draft-07 schemas) —
trimmed to the fields this framework supports, extended with the TPU
grammar: `accelerators: tpu-v5e-16`, `topology`, `capacity_type`
(on_demand | spot | reserved | queued), and multislice `num_slices`.
"""
from __future__ import annotations

from typing import Any, Dict


def _case_insensitive_enum(values) -> Dict[str, Any]:
    return {'type': 'string', 'case_insensitive_enum': list(values)}


# One definition for both the canonical 'capacity' key and its
# 'capacity_type' alias — must track cloud_lib.ProvisionMode.
_CAPACITY_SCHEMA: Dict[str, Any] = {
    'type': 'string',
    'enum': ['on_demand', 'spot', 'reserved', 'queued'],
}


_RESOURCES_PROPERTIES: Dict[str, Any] = {
    'infra': {'type': 'string'},       # 'gcp', 'gke', 'local'
    'cloud': {'type': 'string'},       # reference-compat alias for infra
    'region': {'type': 'string'},
    'zone': {'type': 'string'},
    'instance_type': {'type': 'string'},
    'accelerators': {
        'anyOf': [{'type': 'string'}, {'type': 'object'}, {'type': 'null'}],
    },
    'topology': {'type': ['string', 'null']},       # e.g. '4x4', '2x2x4'
    'num_slices': {'type': 'integer', 'minimum': 1},
    'capacity': _CAPACITY_SCHEMA,
    'capacity_type': _CAPACITY_SCHEMA,  # alias for capacity
    'use_spot': {'type': 'boolean'},   # reference-compat alias
    'spot_recovery': {'type': ['string', 'null']},
    'job_recovery': {
        'anyOf': [{'type': 'string'}, {'type': 'object'}, {'type': 'null'}],
    },
    'cpus': {'type': ['string', 'number', 'null']},
    'memory': {'type': ['string', 'number', 'null']},
    'disk_size': {'type': 'integer'},
    'ports': {
        'anyOf': [{'type': 'string'}, {'type': 'integer'},
                  {'type': 'array'}, {'type': 'null'}],
    },
    'labels': {'type': 'object'},
    'image_id': {'type': ['string', 'object', 'null']},
    'runtime_version': {'type': ['string', 'null']},  # TPU software version
    'reservation': {'type': ['string', 'null']},
    'accelerator_args': {'type': ['object', 'null']},
}


def get_resources_schema() -> Dict[str, Any]:
    return {
        '$schema': 'http://json-schema.org/draft-07/schema#',
        'type': 'object',
        'additionalProperties': False,
        'properties': _RESOURCES_PROPERTIES,
    }


def get_storage_schema() -> Dict[str, Any]:
    return {
        '$schema': 'http://json-schema.org/draft-07/schema#',
        'type': 'object',
        'additionalProperties': False,
        'properties': {
            'name': {'type': 'string'},
            'source': {
                'anyOf': [{'type': 'string'},
                          {'type': 'array', 'items': {'type': 'string'}}],
            },
            'store': {'type': 'string', 'enum': ['gcs', 's3', 'local']},
            'persistent': {'type': 'boolean'},
            'mode': {'type': 'string',
                     'enum': ['MOUNT', 'COPY', 'mount', 'copy']},
            '_force_delete': {'type': 'boolean'},
        },
    }


def get_service_schema() -> Dict[str, Any]:
    return {
        '$schema': 'http://json-schema.org/draft-07/schema#',
        'type': 'object',
        'additionalProperties': False,
        'required': ['readiness_probe'],
        'properties': {
            'readiness_probe': {
                'anyOf': [{'type': 'string'}, {
                    'type': 'object',
                    'additionalProperties': False,
                    'required': ['path'],
                    'properties': {
                        'path': {'type': 'string'},
                        'initial_delay_seconds': {'type': 'number'},
                        'timeout_seconds': {'type': 'number'},
                        'post_data': {'type': ['string', 'object']},
                    },
                }],
            },
            'replica_policy': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'min_replicas': {'type': 'integer', 'minimum': 0},
                    'max_replicas': {'type': 'integer', 'minimum': 0},
                    'target_qps_per_replica': {'type': 'number'},
                    'target_slot_utilization': {
                        'type': 'number',
                        'exclusiveMinimum': 0,
                        'maximum': 1,
                    },
                    'upscale_delay_seconds': {'type': 'number'},
                    'downscale_delay_seconds': {'type': 'number'},
                    'base_ondemand_fallback_replicas': {'type': 'integer'},
                    'use_ondemand_fallback': {'type': 'boolean'},
                },
            },
            'replicas': {'type': 'integer'},
        },
    }


def get_task_schema() -> Dict[str, Any]:
    return {
        '$schema': 'http://json-schema.org/draft-07/schema#',
        'type': 'object',
        'additionalProperties': False,
        'properties': {
            'name': {'type': ['string', 'null']},
            'workdir': {'type': ['string', 'null']},
            'setup': {'type': ['string', 'null']},
            'run': {'type': ['string', 'null']},
            'envs': {'type': 'object',
                     'additionalProperties': {'type': ['string', 'number',
                                                       'boolean', 'null']}},
            'num_nodes': {'type': ['integer', 'null']},
            'resources': {'type': ['object', 'null']},
            'file_mounts': {'type': ['object', 'null']},
            'storage_mounts': {'type': ['object', 'null']},
            'service': {'type': ['object', 'null']},
            'experimental': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {'config_overrides': {'type': 'object'}},
            },
        },
    }


def get_config_schema() -> Dict[str, Any]:
    """Schema for $SKYTPU_HOME/config.yaml."""
    controller_resources = {
        'type': 'object',
        'additionalProperties': False,
        'properties': {
            'controller': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'resources': {'type': 'object'},
                    # 'process' (local daemon) | 'cluster' (controller VM)
                    'mode': {'type': 'string',
                             'enum': ['process', 'cluster']},
                },
            },
        },
    }
    return {
        '$schema': 'http://json-schema.org/draft-07/schema#',
        'type': 'object',
        'additionalProperties': False,
        'properties': {
            'jobs': controller_resources,
            'serve': controller_resources,
            'tpu': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'runtime_version': {'type': 'string'},
                    'provision_mode': {
                        'type': 'string',
                        'enum': ['direct', 'queued', 'auto'],
                    },
                },
            },
            'gcp': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'project_id': {'type': 'string'},
                    'labels': {'type': 'object'},
                    'managed_instance_group': {'type': 'object'},
                },
            },
            'gke': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'cluster': {'type': 'string'},
                    'location': {'type': 'string'},
                    'namespace': {'type': 'string'},
                    'context': {'type': 'string'},
                },
            },
            'kubernetes': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'context': {'type': 'string'},
                    'namespace': {'type': 'string'},
                    'image': {'type': 'string'},
                    'gpu_resource_key': {'type': 'string'},
                    'gpu_label': {'type': 'string'},
                },
            },
            'nvidia_gpus': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {'disable': {'type': 'boolean'}},
            },
            'allowed_clouds': {
                'type': 'array',
                'items': {'type': 'string'},
            },
            'admin_policy': {'type': 'string'},
        },
    }
