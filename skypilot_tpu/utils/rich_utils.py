"""Interactive status spinners for long-running CLI operations.

Parity: /root/reference/sky/utils/rich_utils.py (`safe_status`,
`force_update_status`) — rebuilt dependency-free: a background thread
animates braille frames on stderr when it is a TTY, and degrades to a
single log line when piped/redirected (CI, `sky launch | tee`), so
output stays machine-readable.

Nesting: one live spinner per process; nested `safe_status` calls
update the message of the outer spinner and restore it on exit, the
same contract the reference's client_status provides.
"""
from __future__ import annotations

import contextlib
import sys
import threading
from typing import Iterator, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_FRAMES = '⠋⠙⠹⠸⠼⠴⠦⠧⠇⠏'
_INTERVAL = 0.1

_lock = threading.Lock()
_active: Optional['_Spinner'] = None


class _Spinner:

    def __init__(self, message: str) -> None:
        self.message = message
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        i = 0
        while not self._stop.wait(_INTERVAL):
            with _lock:
                msg = self.message
            frame = _FRAMES[i % len(_FRAMES)]
            sys.stderr.write(f'\r\x1b[2K{frame} {msg}')
            sys.stderr.flush()
            i += 1
        sys.stderr.write('\r\x1b[2K')
        sys.stderr.flush()

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)


def _tty() -> bool:
    try:
        return sys.stderr.isatty()
    except (AttributeError, ValueError):
        return False


@contextlib.contextmanager
def safe_status(message: str, enabled: bool = True) -> Iterator[None]:
    """Show `message` with a spinner while the block runs.

    TTY: animated line on stderr, cleared on exit.  Non-TTY, or
    `enabled=False` (callers streaming subprocess logs — a live
    spinner would rewrite the line their output lands on): one log
    line, nothing else.

    One spinner per PROCESS: the claim-or-nest decision happens
    atomically under the module lock, so concurrent `safe_status`
    blocks (two threads launching different clusters) never start two
    spinners fighting over stderr — later entrants swap the live
    spinner's message for their block's duration and restore it.
    """
    global _active
    if not enabled or not _tty():
        logger.info(message)
        yield
        return
    with _lock:
        outer = _active
        if outer is not None:
            saved = outer.message
            outer.message = message
        else:
            spinner = _Spinner(message)
            _active = spinner
    if outer is not None:
        try:
            yield
        finally:
            with _lock:
                # The owner may have exited first (cross-thread nest);
                # only restore a spinner that is still the live one.
                if _active is outer:
                    outer.message = saved
        return
    spinner.start()
    try:
        yield
    finally:
        spinner.stop()
        with _lock:
            _active = None


def force_update_status(message: str) -> None:
    """Change the live spinner's message (no-op without one)."""
    with _lock:
        if _active is not None:
            _active.message = message
        else:
            logger.info(message)
