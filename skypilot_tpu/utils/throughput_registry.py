"""Measured-throughput registry: data-backed fungibility priors.

VERDICT r2 weak #8: the optimizer's TPU-vs-GPU decisions rode a
hard-coded peak-TFLOPs table, implicitly assuming identical MFU
everywhere.  This registry separates the two factors:

    effective TFLOPs = peak bf16 TFLOPs x MFU factor

where the MFU factor comes from MEASURED bench runs when available
(bench.py records its result here after every real-hardware run) and
falls back to conservative public-experience defaults per accelerator
family.  The optimizer's `_relative_throughput` and the plan table's
estimated-time column both consume `effective_tflops`.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# Conservative defaults (fraction of peak dense-bf16 actually sustained
# in LLM training) per accelerator key; measured records override.
# TPU numbers reflect this repo's own bench lineage; GPU numbers are
# typical well-tuned large-model MFUs from public reports.
DEFAULT_MFU: Dict[str, float] = {
    'tpu-v6e': 0.40, 'tpu-v5p': 0.45, 'tpu-v5e': 0.34, 'tpu-v4': 0.40,
    'tpu-v3': 0.35, 'tpu-v2': 0.30,
    'H100': 0.40, 'H100-MEGA': 0.40, 'A100': 0.45, 'A100-80GB': 0.45,
    'A10G': 0.30, 'L4': 0.30, 'T4': 0.25, 'V100': 0.35,
}
_FALLBACK_MFU = 0.30


def _registry_path() -> str:
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    return os.path.join(
        common_utils.ensure_dir(
            os.path.join(common_utils.skytpu_home(), 'usage')),
        'measured_throughput.json')


def _load() -> Dict[str, Any]:
    try:
        with open(_registry_path(), encoding='utf-8') as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def record_measurement(accelerator_key: str, mfu: float, *,
                       tokens_per_sec: Optional[float] = None,
                       model: Optional[str] = None,
                       source: str = 'bench') -> None:
    """Persist a measured MFU for an accelerator (newest wins)."""
    data = _load()
    data[accelerator_key] = {
        'mfu': round(float(mfu), 4),
        'tokens_per_sec': tokens_per_sec,
        'model': model,
        'source': source,
        'measured_at': time.time(),
    }
    try:
        with open(_registry_path(), 'w', encoding='utf-8') as f:
            json.dump(data, f, indent=1)
    except OSError as e:
        logger.debug(f'throughput registry write failed: {e}')


def mfu_for(accelerator_key: str) -> float:
    """Measured MFU when available, else the family default."""
    rec = _load().get(accelerator_key)
    if rec and rec.get('mfu'):
        return float(rec['mfu'])
    return DEFAULT_MFU.get(accelerator_key, _FALLBACK_MFU)


def is_measured(accelerator_key: str) -> bool:
    rec = _load().get(accelerator_key)
    return bool(rec and rec.get('mfu'))


def device_kind_to_key(device_kind: str) -> Optional[str]:
    """'TPU v5 lite' -> 'tpu-v5e' (bench.py's device strings)."""
    kind = device_kind.lower()
    table = (
        ('v6', 'tpu-v6e'), ('v5p', 'tpu-v5p'), ('v5 lite', 'tpu-v5e'),
        ('v5e', 'tpu-v5e'), ('v4', 'tpu-v4'), ('v3', 'tpu-v3'),
        ('v2', 'tpu-v2'),
    )
    if 'tpu' in kind:
        for frag, key in table:
            if frag in kind:
                return key
    return None
