"""Subprocess helpers: logged execution and bounded parallel fan-out.

Parity: /root/reference/sky/utils/subprocess_utils.py (run_in_parallel,
process-tree kill) — the fan-out primitive used for gang operations across
all hosts of a TPU slice.
"""
from __future__ import annotations

import os
import resource
import shlex
import subprocess
from concurrent import futures
from typing import Any, Callable, Iterable, List, Optional, Tuple, Union

import psutil

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


def get_parallel_threads() -> int:
    """Cap parallelism; ssh fan-out to 64 slice hosts should not fork-bomb."""
    cpu_count = os.cpu_count() or 8
    return max(4, min(cpu_count, 32))


def run_in_parallel(func: Callable,
                    args: Iterable[Any],
                    num_threads: Optional[int] = None) -> List[Any]:
    """Map func over args with a thread pool; preserves order; re-raises."""
    args = list(args)
    if not args:
        return []
    if len(args) == 1:
        return [func(args[0])]
    n = num_threads or get_parallel_threads()
    with futures.ThreadPoolExecutor(max_workers=min(n, len(args))) as pool:
        return list(pool.map(func, args))


def run(cmd: Union[str, List[str]], **kwargs: Any) -> subprocess.CompletedProcess:
    shell = isinstance(cmd, str)
    kwargs.setdefault('shell', shell)
    kwargs.setdefault('check', True)
    kwargs.setdefault('executable', '/bin/bash' if shell else None)
    if not shell:
        kwargs.pop('executable', None)
    return subprocess.run(cmd, **kwargs)


def run_no_outputs(cmd: Union[str, List[str]], **kwargs: Any):
    return run(cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
               **kwargs)


def handle_returncode(returncode: int,
                      command: str,
                      error_msg: Union[str, Callable[[], str]],
                      stderr: Optional[str] = None,
                      stream_logs: bool = True) -> None:
    if returncode == 0:
        return
    echo = logger.error if stream_logs else logger.debug
    if stderr:
        echo(stderr)
    msg = error_msg() if callable(error_msg) else error_msg
    raise exceptions.CommandError(returncode, command, msg, stderr)


def kill_children_processes(parent_pids: Optional[List[int]] = None,
                            force: bool = False) -> None:
    """Kill whole process trees (orphan prevention on job cancel).

    Parity: reference subprocess_daemon.py:40-80 — kill the user job's
    descendants so `cancel` never leaves stray trainers holding TPU chips
    (a leaked process keeps libtpu locked and bricks the slice for the
    next job, so this matters more on TPU than on GPU).
    """
    if parent_pids is None:
        parent_pids = [os.getpid()]
    procs: List[psutil.Process] = []
    for pid in parent_pids:
        try:
            parent = psutil.Process(pid)
        except psutil.NoSuchProcess:
            continue
        procs.extend(parent.children(recursive=True))
        if pid != os.getpid():
            procs.append(parent)
    for proc in procs:
        try:
            if force:
                proc.kill()
            else:
                proc.terminate()
        except psutil.NoSuchProcess:
            pass
    gone, alive = psutil.wait_procs(procs, timeout=5)
    del gone
    for proc in alive:
        try:
            proc.kill()
        except psutil.NoSuchProcess:
            pass


def kill_process_daemon(process_pid: int) -> None:
    """Spawn a detached watcher that reaps `process_pid`'s tree if we die."""
    daemon_script = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                                 'skylet', 'subprocess_daemon.py')
    python = shlex.quote(os.environ.get('SKYTPU_PYTHON', 'python3'))
    subprocess.Popen(
        f'{python} {shlex.quote(daemon_script)} '
        f'--parent-pid {os.getpid()} --proc-pid {process_pid}',
        shell=True,
        stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True)


def get_max_workers_for_file_mounts(num_items: int) -> int:
    fd_limit, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    fd_per_rsync = 5
    max_workers = max(1, (fd_limit - 100) // fd_per_rsync)
    return min(max_workers, num_items, get_parallel_threads())


def run_with_retries(cmd: str,
                     max_retry: int = 3,
                     retry_returncode: Optional[List[int]] = None,
                     retry_stderrs: Optional[List[str]] = None
                     ) -> Tuple[int, str, str]:
    """Run a shell command, retrying on specified returncodes/stderr patterns."""
    retry_cnt = 0
    while True:
        proc = subprocess.run(cmd, shell=True, executable='/bin/bash',
                              capture_output=True, text=True, check=False)
        stdout, stderr = proc.stdout, proc.stderr
        if proc.returncode == 0:
            return 0, stdout, stderr
        retry_cnt += 1
        if retry_cnt > max_retry:
            return proc.returncode, stdout, stderr
        should_retry = False
        if retry_returncode and proc.returncode in retry_returncode:
            should_retry = True
        if retry_stderrs and any(s in stderr for s in retry_stderrs):
            should_retry = True
        if not should_retry:
            return proc.returncode, stdout, stderr
        logger.debug(f'Retrying ({retry_cnt}/{max_retry}): {cmd}')
