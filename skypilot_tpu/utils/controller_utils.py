"""Shared jobs/serve controller machinery: file-mount translation.

Parity: /root/reference/sky/utils/controller_utils.py:679
(`maybe_translate_local_file_mounts_and_sync_up`).  A controller
cluster/VM has no access to the user's laptop filesystem, so every
local path a task references (workdir, local file_mounts, local
storage-mount sources) is rewritten into an auto-created bucket before
the task is handed to the controller:

- workdir             -> bucket/workdir            (COPY at ~/sky_workdir)
- local file_mounts   -> bucket/local-file-mounts/i (COPY at each dst)
- local storage srcs  -> uploaded into their own store

The store type comes from the `<jobs|serve>.bucket` config key (a
`gs://` / `s3://` / `local://` URL, reference config parity); `local://`
pairs with the local provisioner so the whole flow is hermetically
testable.
"""
from __future__ import annotations

import getpass
import os
import re
import uuid
from typing import Optional, Tuple

from skypilot_tpu import config as config_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.skylet import constants as skylet_constants

logger = sky_logging.init_logger(__name__)

_INVALID_BUCKET_CHARS = re.compile(r'[^a-z0-9-]')


def _auto_bucket_name(task_type: str, run_id: str) -> str:
    user = _INVALID_BUCKET_CHARS.sub('-', getpass.getuser().lower())[:16]
    return f'skytpu-{task_type}-{user}-{run_id}'


def _configured_store(task_type: str) -> Tuple[storage_lib.StoreType,
                                               Optional[str]]:
    """-> (store type, fixed bucket name or None) from `<type>.bucket`."""
    url = config_lib.get_nested((task_type, 'bucket'), None)
    if url is None:
        return storage_lib.StoreType.GCS, None
    store_type = storage_lib.StoreType.from_url(url)
    import urllib.parse  # pylint: disable=import-outside-toplevel
    name = urllib.parse.urlsplit(url).netloc or None
    return store_type, name


def maybe_translate_local_file_mounts_and_sync_up(
        task: 'task_lib.Task', task_type: str = 'jobs') -> 'task_lib.Task':
    """Rewrite local paths into bucket-backed storage mounts, in place.

    No-op for tasks that reference nothing local.  Uploads happen here
    (client side, where the files live); the controller/task cluster
    later copies them down from the bucket.
    """
    has_local_file_mounts = any(
        not src.startswith(storage_lib.BUCKET_URL_PREFIXES)
        for src in task.file_mounts.values())
    local_storage_srcs = {
        dst: storage for dst, storage in task.storage_mounts.items()
        if storage.source is not None and
        not storage.stores and
        not str(storage.source).startswith(
            storage_lib.BUCKET_URL_PREFIXES)
    }
    if (task.workdir is None and not has_local_file_mounts and
            not local_storage_srcs):
        return task

    store_type, fixed_name = _configured_store(task_type)
    run_id = uuid.uuid4().hex[:8]
    bucket_name = fixed_name or _auto_bucket_name(task_type, run_id)
    # One bucket per translated task; sub-prefixes keep workdir and each
    # file mount separate (reference uses one bucket with sub-dirs too).
    subdir = f'{task.name or "task"}-{run_id}'

    def _mount(prefix: str, local_src: str) -> storage_lib.Storage:
        store_cls = storage_lib._STORE_CLASSES[store_type]  # pylint: disable=protected-access
        store = store_cls(bucket_name, local_src,
                          prefix=f'{subdir}/{prefix}')
        store.create()
        store.upload(local_src)
        # source = the store's bucket URL (incl. prefix) so the mount
        # survives the DAG-YAML round-trip to the controller: the
        # controller re-creates the exact store from the URL alone.
        storage = storage_lib.Storage(
            name=bucket_name, source=store.url,
            stores={store_type: store},
            persistent=False, mode=storage_lib.StorageMode.COPY)
        return storage

    if task.workdir is not None:
        workdir = task.workdir
        task.workdir = None
        task.storage_mounts[skylet_constants.SKY_REMOTE_WORKDIR] = _mount(
            'workdir', workdir)
        logger.info(f'Translated workdir {workdir!r} -> '
                    f'{store_type.value} bucket {bucket_name!r}')

    import collections  # pylint: disable=import-outside-toplevel
    import shutil  # pylint: disable=import-outside-toplevel
    import tempfile  # pylint: disable=import-outside-toplevel

    new_file_mounts = {}
    file_dsts_by_parent = collections.defaultdict(list)
    dir_mounts = []
    for dst, src in sorted(task.file_mounts.items()):
        if src.startswith(storage_lib.BUCKET_URL_PREFIXES):
            new_file_mounts[dst] = src
            continue
        expanded = os.path.expanduser(src)
        if os.path.isdir(expanded):
            dir_mounts.append((dst, src))
        else:
            parent = os.path.dirname(dst.rstrip('/')) or '.'
            file_dsts_by_parent[parent].append((dst, expanded))
        logger.info(f'Translating file_mount {src!r} -> '
                    f'{store_type.value} bucket {bucket_name!r}')
    translated_dir_mounts = {}
    for i, (dst, src) in enumerate(dir_mounts):
        translated_dir_mounts[dst.rstrip('/')] = task.storage_mounts[dst] \
            = _mount(f'local-file-mounts/{i}', src)
    # Single files are staged under their DESTINATION basename, one
    # staging dir per remote parent dir, so the copy-down of the prefix
    # into the parent lands every file at exactly its dst (src and dst
    # basenames may differ; multiple files may share a parent).
    for i, (parent, entries) in enumerate(
            sorted(file_dsts_by_parent.items())):
        with tempfile.TemporaryDirectory() as staging:
            for dst, expanded in entries:
                shutil.copy2(
                    expanded,
                    os.path.join(staging,
                                 os.path.basename(dst.rstrip('/'))))
            if parent.rstrip('/') in translated_dir_mounts:
                # {'/data': dir, '/data/cfg.yaml': file}: add the staged
                # file(s) into the already-translated dir mount's bucket
                # prefix instead of clobbering that mount.
                store = translated_dir_mounts[
                    parent.rstrip('/')].get_default_store()
                for name in os.listdir(staging):
                    store.upload(os.path.join(staging, name))
            elif parent in task.storage_mounts:
                raise ValueError(
                    f'file_mounts place single file(s) under {parent!r}, '
                    f'which already has a storage mount; move the files '
                    f'or mount the bucket elsewhere.')
            else:
                task.storage_mounts[parent] = _mount(
                    f'local-single-files/{i}', staging)
    task.file_mounts = new_file_mounts

    # Storage mounts whose source is a local path and which have no
    # store yet: attach the configured store (add_store uploads).
    for dst, storage in local_storage_srcs.items():
        storage.add_store(store_type)
        logger.info(f'Uploaded storage mount source {storage.source!r} '
                    f'for {dst!r}')
    return task
