"""Canonical accelerator names and the TPU topology model.

Parity: /root/reference/sky/utils/accelerator_registry.py:1-118 (canonical
names, `is_schedulable_non_gpu_accelerator`) — but where the reference treats
TPUs as an opaque custom Ray resource, here the *slice* is the first-class
scheduling unit: every TPU accelerator string (``tpu-v5p-64``) resolves to a
:class:`TpuSliceSpec` carrying chips/hosts/topology/HBM, which the backend
uses for gang sizing and the compute layer uses for mesh construction.

Naming grammar (canonical, lower-case):
    tpu-v2-8, tpu-v3-32, tpu-v4-128, tpu-v5e-16, tpu-v5p-64, tpu-v6e-256
The trailing number follows Google's public convention: TensorCore count for
v2/v3/v4/v5p, chip count for v5e/v6e. ``TpuSliceSpec`` normalizes all of this
into chips and hosts so no other layer needs to know the convention.
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, List, Optional, Tuple

# GPUs kept fungible with TPUs in the optimizer (SURVEY.md: BASELINE.json
# north star — "TPU chips as cost/availability-fungible with GPUs").
_CANONICAL_GPUS = (
    'A100', 'A100-80GB', 'H100', 'L4', 'T4', 'V100', 'P100', 'K80',
)

_TPU_NAME_RE = re.compile(r'^tpu-v(?P<gen>[23456])(?P<flavor>[ep]?)-(?P<size>\d+)$')


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    """Per-generation hardware facts used to expand a name into a slice spec.

    Numbers are the public machine shapes: cores_per_chip distinguishes the
    size-suffix convention (v2/v3/v4/v5p count TensorCores, v5e/v6e count
    chips); chips_per_host is the host granularity used for multi-host
    slices; hbm_gib_per_chip bounds what fits for the compute layer.
    """
    name: str                   # 'v5p'
    size_is_cores: bool         # trailing number counts cores (else chips)
    cores_per_chip: int
    chips_per_host: int         # multi-host slice host granularity
    max_single_host_chips: int  # largest slice that is still one host
    hbm_gib_per_chip: float
    bf16_tflops_per_chip: float  # peak dense bf16 (public spec sheets)
    supports_3d_torus: bool     # v4/v5p have 3D ICI torus; others 2D


TPU_GENERATIONS: Dict[str, TpuGeneration] = {
    'v2': TpuGeneration('v2', True, 2, 4, 4, 8.0, 23.0, False),
    'v3': TpuGeneration('v3', True, 2, 4, 4, 16.0, 61.0, False),
    'v4': TpuGeneration('v4', True, 2, 4, 4, 32.0, 137.5, True),
    'v5e': TpuGeneration('v5e', False, 1, 4, 8, 16.0, 98.3, False),
    'v5p': TpuGeneration('v5p', True, 2, 4, 4, 95.0, 229.1, True),
    'v6e': TpuGeneration('v6e', False, 1, 4, 8, 32.0, 459.2, False),
}


@dataclasses.dataclass(frozen=True)
class TpuSliceSpec:
    """A fully-resolved TPU slice: the atomic provisioning unit.

    One handle = one slice = ``num_hosts`` TPU-VM workers (generalizing the
    reference's ``num_ips_per_node``, cloud_vm_ray_backend.py:2475-2483).
    """
    name: str                # canonical 'tpu-v5p-64'
    generation: str          # 'v5p'
    size: int                # the trailing number as written
    num_chips: int
    num_hosts: int
    chips_per_host: int
    topology: Tuple[int, ...]  # ICI torus shape in chips, e.g. (4, 4) / (2, 2, 4)
    hbm_gib_per_chip: float
    bf16_tflops_per_chip: float

    @property
    def is_pod(self) -> bool:
        return self.num_hosts > 1

    @property
    def total_hbm_gib(self) -> float:
        return self.hbm_gib_per_chip * self.num_chips

    @property
    def total_bf16_tflops(self) -> float:
        return self.bf16_tflops_per_chip * self.num_chips

    @property
    def topology_str(self) -> str:
        return 'x'.join(str(d) for d in self.topology)


def _default_topology(gen: TpuGeneration, num_chips: int) -> Tuple[int, ...]:
    """Smallest-surface torus of the right dimensionality for num_chips.

    v4/v5p use a 3D torus built from 2x2x1 host blocks; 2D generations use
    the most-square 2D factorization. This mirrors the default shapes the
    TPU API assigns when no explicit topology is requested.
    """
    if num_chips <= 1:
        return (1,)
    if gen.supports_3d_torus and num_chips >= 8:
        # Factor into (x, y, z) as close to cubic as possible, dims even
        # (hosts are 2x2x1 blocks of 4 chips).
        best = None
        for x in range(2, int(round(num_chips ** (1 / 3))) + 2, 2):
            if num_chips % x:
                continue
            rest = num_chips // x
            for y in range(x, int(math.isqrt(rest)) + 2, 2):
                if rest % y:
                    continue
                z = rest // y
                if z < y:
                    continue
                cand = (x, y, z)
                if best is None or max(cand) < max(best):
                    best = cand
        if best is not None:
            return best
    # 2D: most-square factorization.
    for w in range(int(math.isqrt(num_chips)), 0, -1):
        if num_chips % w == 0:
            return (w, num_chips // w)
    return (1, num_chips)


def parse_tpu_name(name: str) -> Optional[TpuSliceSpec]:
    """'tpu-v5p-64' → TpuSliceSpec, or None if not a TPU name."""
    m = _TPU_NAME_RE.match(name.lower().strip())
    if m is None:
        return None
    gen_key = f"v{m.group('gen')}{m.group('flavor')}"
    gen = TPU_GENERATIONS.get(gen_key)
    if gen is None:
        return None
    size = int(m.group('size'))
    if size <= 0:
        return None
    if gen.size_is_cores and size % gen.cores_per_chip:
        return None  # e.g. 'tpu-v5p-3': core counts must be whole chips
    num_chips = size // gen.cores_per_chip if gen.size_is_cores else size
    if num_chips < 1:
        return None
    if num_chips <= gen.max_single_host_chips:
        num_hosts = 1
        chips_per_host = num_chips
    else:
        if num_chips % gen.chips_per_host:
            return None  # not a valid multi-host shape
        num_hosts = num_chips // gen.chips_per_host
        chips_per_host = gen.chips_per_host
    return TpuSliceSpec(
        name=f'tpu-{gen_key}-{size}',
        generation=gen_key,
        size=size,
        num_chips=num_chips,
        num_hosts=num_hosts,
        chips_per_host=chips_per_host,
        topology=_default_topology(gen, num_chips),
        hbm_gib_per_chip=gen.hbm_gib_per_chip,
        bf16_tflops_per_chip=gen.bf16_tflops_per_chip,
    )


def is_tpu(accelerator_name: Optional[str]) -> bool:
    if accelerator_name is None:
        return False
    return parse_tpu_name(accelerator_name) is not None


def is_tpu_pod(accelerator_name: Optional[str]) -> bool:
    if accelerator_name is None:
        return False
    spec = parse_tpu_name(accelerator_name)
    return spec is not None and spec.is_pod


def canonicalize_accelerator_name(name: str) -> str:
    """Map user spellings to the canonical name.

    Accepts 'TPU-V5P-64', 'tpu-v5litepod-8' (GCP API spelling for v5e),
    'v5e-16' shorthand, and case-insensitive GPU names.
    """
    lowered = name.lower().strip()
    lowered = lowered.replace('v5litepod', 'v5e').replace('v5lite', 'v5e')
    if not lowered.startswith('tpu-') and re.match(r'^v[23456][ep]?-\d+$',
                                                   lowered):
        lowered = f'tpu-{lowered}'
    spec = parse_tpu_name(lowered)
    if spec is not None:
        return spec.name
    for gpu in _CANONICAL_GPUS:
        if lowered == gpu.lower():
            return gpu
    return name


def is_schedulable_non_gpu_accelerator(accelerator_name: str) -> bool:
    """TPUs are scheduled as slices (host gangs), not device-count GPUs.

    Parity: reference accelerator_registry.py's same-named predicate, used to
    route TPU jobs away from `num_gpus` scheduling
    (cloud_vm_ray_backend.py:396,565).
    """
    return is_tpu(accelerator_name)


def list_tpu_names(max_chips: int = 4096) -> List[str]:
    """All valid canonical TPU names up to max_chips (for catalog/docs)."""
    names = []
    for gen_key, gen in TPU_GENERATIONS.items():
        chips = 1
        while chips <= max_chips:
            if chips <= gen.max_single_host_chips or (
                    chips % gen.chips_per_host == 0):
                size = chips * gen.cores_per_chip if gen.size_is_cores else chips
                spec = parse_tpu_name(f'tpu-{gen_key}-{size}')
                if spec is not None:
                    names.append(spec.name)
            chips *= 2
    return names
