"""Chrome trace-event timeline for control-plane profiling.

Parity: /root/reference/sky/utils/timeline.py:1-133 — `@timeline.event`
decorated spans plus FileLock contention spans, dumped as a Chrome
trace-event JSON when SKYTPU_TIMELINE_FILE is set.

Enabling is no longer import-time-only: `start(path)` turns recording
on programmatically, and `save_timeline()` re-checks the env var so a
process that sets SKYTPU_TIMELINE_FILE after this module imported
still gets its dump.  The serving request spans
(observability/tracing.py) emit completed phases here via
`add_complete_event`, so one chrome://tracing load shows control-plane
spans and per-request queue/prefill/decode phases on a shared clock.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, List, Optional, Union

import filelock

_events: List[dict] = []
_events_lock = threading.Lock()
_enabled_path: Optional[str] = None
_atexit_registered = False


def _now_us() -> int:
    return int(time.time() * 10**6)


def _register_atexit_once() -> None:
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(save_timeline)


def start(path: str) -> None:
    """Enable recording to `path` (programmatic alternative to setting
    SKYTPU_TIMELINE_FILE before import); registers the atexit dump
    exactly once no matter how often enabling happens."""
    global _enabled_path
    _enabled_path = path
    _register_atexit_once()


def enabled() -> bool:
    return _active_path() is not None


def _active_path() -> Optional[str]:
    """The dump path, honoring an env var set AFTER import (late
    enabling was silently ignored before)."""
    if _enabled_path is not None:
        return _enabled_path
    return os.environ.get('SKYTPU_TIMELINE_FILE')


class Event:
    """A named span; use as a context manager or via the @event decorator."""

    def __init__(self, name: str, message: Optional[str] = None) -> None:
        self._name = name
        self._message = message

    def begin(self) -> None:
        self._record('B')

    def end(self) -> None:
        self._record('E')

    def _record(self, phase: str) -> None:
        if _active_path() is None:
            return
        evt = {
            'name': self._name,
            'cat': 'default',
            'ph': phase,
            'ts': _now_us(),
            'pid': os.getpid(),
            'tid': threading.get_ident(),
        }
        if self._message is not None:
            evt['args'] = {'message': self._message}
        with _events_lock:
            _events.append(evt)

    def __enter__(self) -> 'Event':
        self.begin()
        return self

    def __exit__(self, *args: Any) -> None:
        self.end()


def event(name_or_fn: Union[str, Callable], message: Optional[str] = None):
    """Decorator (or decorator factory) recording the call as a span."""
    if callable(name_or_fn):
        fn = name_or_fn

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with Event(f'{fn.__module__}.{fn.__qualname__}'):
                return fn(*args, **kwargs)

        return wrapper

    def deco(fn: Callable) -> Callable:

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with Event(str(name_or_fn), message):
                return fn(*args, **kwargs)

        return wrapper

    return deco


class FileLockEvent:
    """A filelock whose acquisition wait is recorded as a timeline span."""

    def __init__(self, lockfile: str, timeout: float = -1) -> None:
        self._lockfile = lockfile
        os.makedirs(os.path.dirname(os.path.abspath(lockfile)), exist_ok=True)
        self._lock = filelock.FileLock(lockfile, timeout)
        self._hold_event = Event(f'[FileLock.hold]:{lockfile}')

    def acquire(self) -> None:
        with Event(f'[FileLock.acquire]:{self._lockfile}'):
            self._lock.acquire()
        self._hold_event.begin()

    def release(self) -> None:
        self._hold_event.end()
        self._lock.release()

    def __enter__(self) -> 'FileLockEvent':
        self.acquire()
        return self

    def __exit__(self, *args: Any) -> None:
        self.release()


def add_complete_event(name: str, start_s: float, duration_s: float,
                       args: Optional[dict] = None,
                       cat: str = 'request') -> None:
    """Record an already-finished span ('X' complete event): `start_s`
    is wall-clock seconds (time.time()), `duration_s` its length.  Used
    by observability/tracing.py, whose phases are only known in
    retrospect (queue wait ends when the engine admits the request)."""
    if _active_path() is None:
        return
    evt = {
        'name': name,
        'cat': cat,
        'ph': 'X',
        'ts': int(start_s * 10**6),
        'dur': max(0, int(duration_s * 10**6)),
        'pid': os.getpid(),
        'tid': threading.get_ident(),
    }
    if args:
        evt['args'] = args
    with _events_lock:
        _events.append(evt)


def write_trace(path: str, trace_events: List[dict]) -> None:
    """Write an arbitrary list of Chrome trace events as a standalone
    trace file — the flight-recorder journal export
    (observability/events.py) renders through this, independent of the
    live-recording buffer above."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump({'traceEvents': list(trace_events)}, f)


def save_timeline() -> None:
    # Re-check the env var: a path set after import (programmatic
    # runs, tests) must still produce a dump.
    path = _active_path()
    if path is None or not _events:
        return
    with _events_lock:
        payload = {'traceEvents': list(_events)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(payload, f)


if os.environ.get('SKYTPU_TIMELINE_FILE') is not None:
    _register_atexit_once()
