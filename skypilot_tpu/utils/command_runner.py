"""Command runners: how the client talks to slice hosts.

Parity: /root/reference/sky/utils/command_runner.py:158-857 (`CommandRunner`
ABC, `SSHCommandRunner` with ControlMaster multiplexing and rsync). TPU-first
additions: a `LocalProcessRunner` that emulates a slice host as a local
directory + subprocess — the hermetic-test provisioner (SURVEY.md §4 calls out
that the reference has no fake provisioner; we fix that) — and gang helpers
that fan a command out to every worker of a slice in parallel.
"""
from __future__ import annotations

import enum
import hashlib
import os
import pathlib
import shlex
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

# run_with_retry defaults: 3 attempts, ~1s/1.6x capped exponential
# backoff with jitter (common_utils.Backoff).
DEFAULT_MAX_ATTEMPTS = 3
_RETRY_INITIAL_BACKOFF_SECONDS = 1.0

GIT_EXCLUDE = '.git/info/exclude'
RSYNC_DISPLAY_OPTION = '-Pavz'
RSYNC_FILTER_OPTION = '--filter=\'dir-merge,- .gitignore\''
RSYNC_EXCLUDE_OPTION = '--exclude-from={}'

_DEFAULT_CONNECT_TIMEOUT = 30


def ssh_options_list(ssh_private_key: Optional[str],
                     ssh_control_name: Optional[str],
                     *,
                     ssh_proxy_command: Optional[str] = None,
                     connect_timeout: Optional[int] = None,
                     port: int = 22,
                     disable_control_master: bool = False) -> List[str]:
    """Standard ssh options: batch mode, multiplexing, no host-key prompts."""
    if connect_timeout is None:
        connect_timeout = _DEFAULT_CONNECT_TIMEOUT
    arg_dict: Dict[str, Any] = {
        'StrictHostKeyChecking': 'no',
        'UserKnownHostsFile': '/dev/null',
        'IdentitiesOnly': 'yes',
        'ExitOnForwardFailure': 'yes',
        'ServerAliveInterval': 5,
        'ServerAliveCountMax': 3,
        'ConnectTimeout': f'{connect_timeout}s',
        'ForwardAgent': 'yes',
        'Port': port,
    }
    if ssh_control_name is not None and not disable_control_master:
        arg_dict.update({
            'ControlMaster': 'auto',
            'ControlPath': f'{_ssh_control_path(ssh_control_name)}/%C',
            'ControlPersist': '300s',
        })
    ssh_key_option = ['-i', ssh_private_key] if ssh_private_key else []
    proxy = []
    if ssh_proxy_command is not None:
        proxy = ['-o', f'ProxyCommand={ssh_proxy_command}']
    return ssh_key_option + [
        x for k, v in arg_dict.items() for x in ('-o', f'{k}={v}')
    ] + proxy


def _runner_retries():
    from skypilot_tpu.observability import metrics  # pylint: disable=import-outside-toplevel
    return metrics.counter('skytpu_runner_retries_total',
                           'Transient command-runner exec retries')


def _ssh_control_path(ssh_control_filename: str) -> str:
    path = f'/tmp/skytpu_ssh_{common_utils.get_user_hash()}/{ssh_control_filename}'
    os.makedirs(path, exist_ok=True)
    return path


class SshMode(enum.Enum):
    NON_INTERACTIVE = 0
    INTERACTIVE = 1
    LOGIN = 2


class CommandRunner:
    """Abstract transport to one slice host: run commands and sync files."""

    # Return codes of `run` that mean the TRANSPORT failed (not the
    # command): worth a retry.  Empty for local/kubectl transports —
    # their exit code is the command's own.
    TRANSIENT_RETURNCODES: Tuple[int, ...] = ()

    def __init__(self, node: Tuple[Any, ...], **kwargs: Any) -> None:
        del kwargs
        self.node = node

    @property
    def node_id(self) -> str:
        return '-'.join(str(x) for x in self.node)

    def run(self,
            cmd: Union[str, List[str]],
            *,
            require_outputs: bool = False,
            log_path: str = os.devnull,
            stream_logs: bool = True,
            process_stream: bool = True,
            **kwargs: Any) -> Union[int, Tuple[int, str, str]]:
        raise NotImplementedError

    def run_with_retry(self,
                       cmd: Union[str, List[str]],
                       *,
                       max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                       on_retry: Optional[Any] = None,
                       **kwargs: Any) -> Union[int, Tuple[int, str, str]]:
        """`run` with transient-failure retries.

        One ssh blip must not fail a whole gang: a transport-level
        failure (TransientRunnerError, or a returncode in
        TRANSIENT_RETURNCODES — ssh's 255) is retried up to
        `max_attempts` times with capped exponential backoff + jitter.
        The command's own non-zero exits pass through untouched.
        `on_retry(attempt, reason)` lets callers journal each retry;
        exhaustion raises TransientRunnerError carrying the attempt
        count.
        """
        backoff = common_utils.Backoff(_RETRY_INITIAL_BACKOFF_SECONDS,
                                       max_backoff_factor=3)
        last_error = 'unknown transient failure'
        for attempt in range(1, max_attempts + 1):
            try:
                chaos_injector.inject('runner.exec', node=self.node_id,
                                      attempt=attempt)
                result = self.run(cmd, **kwargs)
            except exceptions.TransientRunnerError as e:
                last_error = str(e)
            else:
                rc = result[0] if isinstance(result, tuple) else result
                if rc not in self.TRANSIENT_RETURNCODES:
                    return result
                last_error = (f'transport returned transient code {rc} '
                              f'(node {self.node_id})')
            if attempt == max_attempts:
                break
            if on_retry is not None:
                on_retry(attempt, last_error)
            _runner_retries().inc()
            logger.warning(f'Transient exec failure on {self.node_id} '
                           f'(attempt {attempt}/{max_attempts}): '
                           f'{last_error}; retrying.')
            time.sleep(backoff.current_backoff)
        raise exceptions.TransientRunnerError(
            f'Exec on {self.node_id} failed after {max_attempts} '
            f'attempts: {last_error}', attempts=max_attempts)

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = os.devnull, stream_logs: bool = True) -> None:
        raise NotImplementedError

    def spawn_spec(self, cmd: str) -> Optional[List[str]]:
        """argv that runs `cmd` on this node as a standalone child
        process (for the native gang fan-in); None when the runner
        cannot express itself as a plain argv."""
        del cmd
        return None

    def check_connection(self) -> bool:
        returncode = self.run('true', connect_timeout=5, stream_logs=False,
                              require_outputs=False)
        return returncode == 0

    def close_cached_connection(self) -> None:
        pass

    @staticmethod
    def _rsync_exclude_args(source: str) -> List[str]:
        """Respect .gitignore via rsync dir-merge filters + .git/info/exclude."""
        args = [RSYNC_FILTER_OPTION]
        exclude = os.path.join(os.path.expanduser(source), GIT_EXCLUDE)
        if os.path.isfile(exclude):
            args.append(RSYNC_EXCLUDE_OPTION.format(shlex.quote(exclude)))
        skyignore = os.path.join(os.path.expanduser(source), '.skyignore')
        if os.path.isfile(skyignore):
            args.append(RSYNC_EXCLUDE_OPTION.format(shlex.quote(skyignore)))
        return args


def _run_local(cmd: List[str] | str, *, shell: bool, require_outputs: bool,
               log_path: str, stream_logs: bool,
               env: Optional[Dict[str, str]] = None,
               cwd: Optional[str] = None,
               on_spawn: Optional[Any] = None
               ) -> Union[int, Tuple[int, str, str]]:
    """Shared subprocess execution with tee-to-logfile semantics."""
    from skypilot_tpu.skylet import log_lib  # pylint: disable=import-outside-toplevel
    return log_lib.run_with_log(cmd,
                                log_path,
                                require_outputs=require_outputs,
                                stream_logs=stream_logs,
                                shell=shell,
                                env=env,
                                cwd=cwd,
                                on_spawn=on_spawn)


class SSHCommandRunner(CommandRunner):
    """Runner for real TPU-VM workers over ssh with ControlMaster reuse.

    Parity: reference command_runner.py:399-654.
    """

    # ssh exits 255 on transport failure (connection refused/reset,
    # auth churn during VM boot); the command's own exits are 0-254.
    TRANSIENT_RETURNCODES = (255,)

    def __init__(self,
                 node: Tuple[str, int],
                 ssh_user: str,
                 ssh_private_key: str,
                 ssh_control_name: Optional[str] = '__default__',
                 ssh_proxy_command: Optional[str] = None,
                 disable_control_master: bool = False,
                 **kwargs: Any) -> None:
        super().__init__(node)
        self.ip, self.port = node[0], node[1] if len(node) > 1 else 22
        self.ssh_user = ssh_user
        self.ssh_private_key = ssh_private_key
        self.ssh_control_name = (None if ssh_control_name is None else
                                 hashlib.md5(ssh_control_name.encode()).hexdigest()[:10])
        self._ssh_proxy_command = ssh_proxy_command
        self.disable_control_master = disable_control_master
        del kwargs

    @classmethod
    def make_runner_list(cls, node_list: List[Tuple[str, int]],
                         **common_kwargs: Any) -> List['SSHCommandRunner']:
        return [cls(node, **common_kwargs) for node in node_list]

    def _ssh_base_command(self, *, ssh_mode: SshMode,
                          connect_timeout: Optional[int]) -> List[str]:
        ssh = ['ssh']
        if ssh_mode == SshMode.NON_INTERACTIVE:
            ssh += ['-T']
        else:
            ssh += ['-tt']
        return ssh + ssh_options_list(
            self.ssh_private_key,
            self.ssh_control_name,
            ssh_proxy_command=self._ssh_proxy_command,
            port=self.port,
            connect_timeout=connect_timeout,
            disable_control_master=self.disable_control_master) + [
                f'{self.ssh_user}@{self.ip}'
            ]

    def run(self,
            cmd: Union[str, List[str]],
            *,
            require_outputs: bool = False,
            port_forward: Optional[List[int]] = None,
            log_path: str = os.devnull,
            stream_logs: bool = True,
            ssh_mode: SshMode = SshMode.NON_INTERACTIVE,
            connect_timeout: Optional[int] = None,
            source_bashrc: bool = False,
            **kwargs: Any) -> Union[int, Tuple[int, str, str]]:
        on_spawn = kwargs.pop('on_spawn', None)
        del kwargs
        base = self._ssh_base_command(ssh_mode=ssh_mode,
                                      connect_timeout=connect_timeout)
        if port_forward:
            for port in port_forward:
                base += ['-L', f'{port}:localhost:{port}']
        if isinstance(cmd, list):
            cmd = ' '.join(cmd)
        # Run under bash -lc so PATH includes ~/.local/bin etc.
        shell_prefix = 'bash --login -c' if source_bashrc else 'bash -c'
        command = base + [f'{shell_prefix} {shlex.quote(cmd)}']
        return _run_local(command, shell=False,
                          require_outputs=require_outputs, log_path=log_path,
                          stream_logs=stream_logs, on_spawn=on_spawn)

    def spawn_spec(self, cmd: str) -> Optional[List[str]]:
        base = self._ssh_base_command(ssh_mode=SshMode.NON_INTERACTIVE,
                                      connect_timeout=None)
        return base + [f'bash -c {shlex.quote(cmd)}']

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = os.devnull, stream_logs: bool = True) -> None:
        rsync_command = ['rsync', RSYNC_DISPLAY_OPTION]
        if up:
            rsync_command += self._rsync_exclude_args(source)
        ssh_options = ' '.join(
            ssh_options_list(self.ssh_private_key,
                             self.ssh_control_name,
                             ssh_proxy_command=self._ssh_proxy_command,
                             port=self.port,
                             disable_control_master=self.disable_control_master))
        rsync_command.append(f'-e "ssh {ssh_options}"')
        if up:
            rsync_command += [source, f'{self.ssh_user}@{self.ip}:{target}']
        else:
            rsync_command += [f'{self.ssh_user}@{self.ip}:{source}', target]
        command = ' '.join(rsync_command)
        returncode, _, stderr = subprocess_utils.run_with_retries(
            command, max_retry=3,
            retry_stderrs=['ssh_exchange_identification',
                           'Connection refused'])
        direction = 'up' if up else 'down'
        subprocess_utils.handle_returncode(
            returncode, command,
            f'Failed to rsync {direction}: {source} -> {target}', stderr,
            stream_logs)

    def close_cached_connection(self) -> None:
        if self.ssh_control_name is None:
            return
        control_path = _ssh_control_path(self.ssh_control_name)
        subprocess.run(f'ssh -O exit -o ControlPath={control_path}/%C '
                       f'-p {self.port} {self.ssh_user}@{self.ip}',
                       shell=True, check=False, capture_output=True)


class LocalProcessRunner(CommandRunner):
    """Emulates one slice host as a directory + subprocesses on this machine.

    The host's filesystem root maps to `root_dir`; '~' in remote paths is
    rewritten under it. Env vars mimic the TPU-VM worker identity
    (TPU_WORKER_ID etc. are injected by the caller via `env`). This is the
    substrate for the `local` provisioner and for all hermetic gang-exec,
    skylet, jobs, and serve tests.
    """

    def __init__(self, node: Tuple[str, int], root_dir: str,
                 env: Optional[Dict[str, str]] = None, **kwargs: Any) -> None:
        super().__init__(node)
        self.root_dir = os.path.abspath(os.path.expanduser(root_dir))
        os.makedirs(self.root_dir, exist_ok=True)
        self._env = dict(env or {})
        del kwargs

    def _map_path(self, path: str) -> str:
        if path.startswith('~'):
            return os.path.join(self.root_dir, path.lstrip('~/'))
        if os.path.isabs(path):
            return path
        return os.path.join(self.root_dir, path)

    def run(self,
            cmd: Union[str, List[str]],
            *,
            require_outputs: bool = False,
            log_path: str = os.devnull,
            stream_logs: bool = True,
            connect_timeout: Optional[int] = None,
            **kwargs: Any) -> Union[int, Tuple[int, str, str]]:
        on_spawn = kwargs.pop('on_spawn', None)
        del connect_timeout, kwargs
        if isinstance(cmd, list):
            cmd = ' '.join(cmd)
        env = {**os.environ, **self._env, 'HOME': self.root_dir}
        # The host's job queue lives under its own HOME; a client-side
        # SKYTPU_JOB_DB override (tests) must not leak in. SKYTPU_HOME *is*
        # inherited on purpose: it is how the emulated host reaches the
        # local provisioner's state, standing in for cloud API access.
        if 'SKYTPU_JOB_DB' not in self._env:
            env.pop('SKYTPU_JOB_DB', None)
        return _run_local(cmd, shell=True, require_outputs=require_outputs,
                          log_path=log_path, stream_logs=stream_logs, env=env,
                          cwd=self.root_dir, on_spawn=on_spawn)

    def spawn_spec(self, cmd: str) -> Optional[List[str]]:
        # env(1) options must precede KEY=VALUE assignments.
        argv = ['env', '-C', self.root_dir]
        if 'SKYTPU_JOB_DB' not in self._env:
            argv += ['-u', 'SKYTPU_JOB_DB']
        argv += [f'HOME={self.root_dir}']
        argv += [f'{k}={v}' for k, v in self._env.items()]
        return argv + ['bash', '-c', cmd]

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = os.devnull, stream_logs: bool = True) -> None:
        # Pure-Python sync (no rsync dependency in hermetic environments).
        if up:
            src, dst = os.path.expanduser(source), self._map_path(target)
        else:
            src, dst = self._map_path(source), os.path.expanduser(target)
        _python_sync(src, dst, apply_excludes=up)


def _python_sync(src: str, dst: str, apply_excludes: bool) -> None:
    """shutil-based directory/file sync honoring .skyignore/.gitignore-style
    top-level patterns (simplified: pattern match on path segments)."""
    import fnmatch  # pylint: disable=import-outside-toplevel
    import shutil  # pylint: disable=import-outside-toplevel
    src = os.path.abspath(src)
    if not os.path.exists(src):
        raise FileNotFoundError(f'Sync source does not exist: {src}')
    if os.path.isfile(src):
        pathlib.Path(dst).parent.mkdir(parents=True, exist_ok=True)
        if os.path.isdir(dst):
            dst = os.path.join(dst, os.path.basename(src))
        shutil.copy2(src, dst)
        return
    patterns: List[str] = ['.git']
    if apply_excludes:
        for ignore_file in ('.skyignore', '.gitignore'):
            path = os.path.join(src, ignore_file)
            if os.path.isfile(path):
                with open(path, encoding='utf-8') as f:
                    for line in f:
                        line = line.strip()
                        if line and not line.startswith('#'):
                            patterns.append(line.rstrip('/').lstrip('/'))

    def _ignore(dirname: str, names: List[str]) -> List[str]:
        del dirname
        ignored = set()
        for name in names:
            for pat in patterns:
                if fnmatch.fnmatch(name, pat):
                    ignored.add(name)
        return list(ignored)

    pathlib.Path(dst).mkdir(parents=True, exist_ok=True)
    shutil.copytree(src, dst, ignore=_ignore, dirs_exist_ok=True)


def run_on_all(runners: List[CommandRunner], cmd: str,
               *, log_dir: Optional[str] = None, stream_logs: bool = False,
               require_outputs: bool = False) -> List[Any]:
    """Gang fan-out: run `cmd` on every host of the slice in parallel.

    Replaces the reference's Ray-task fan-out (cloud_vm_ray_backend.py:535) —
    on TPU the slice membership is fixed by topology, so plain parallel
    transport calls suffice; no placement-group scheduler needed.
    """

    def _one(idx_runner: Tuple[int, CommandRunner]) -> Any:
        idx, runner = idx_runner
        log_path = os.devnull
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f'{idx}-{runner.node_id}.log')
        return runner.run_with_retry(cmd, log_path=log_path,
                                     stream_logs=stream_logs,
                                     require_outputs=require_outputs)

    return subprocess_utils.run_in_parallel(_one, list(enumerate(runners)))


def wait_until_ready(runners: List[CommandRunner], timeout: float = 300,
                     poll_interval: float = 2.0) -> None:
    """Block until every host answers a trivial command (ssh-ready probe).

    Parity: provisioner.wait_for_ssh (reference provisioner.py:215-390).
    """
    deadline = time.time() + timeout
    pending = list(runners)
    while pending:
        pending = [r for r in pending if not r.check_connection()]
        if not pending:
            return
        if time.time() > deadline:
            ids = [r.node_id for r in pending]
            raise TimeoutError(
                f'Hosts not reachable after {timeout}s: {ids}')
        time.sleep(poll_interval)


class KubernetesCommandRunner(CommandRunner):
    """Runner for pods (GKE TPU node-pool hosts) via `kubectl exec`.

    Parity: reference command_runner.py:656-857 (KubernetesCommandRunner) —
    pods stand in for slice hosts; file transfer rides `kubectl exec` + tar
    (no rsync dependency inside minimal TPU images).
    """

    def __init__(self, node: Tuple[str, int], namespace: str = 'default',
                 context: Optional[str] = None, container: Optional[str] = None,
                 **kwargs: Any) -> None:
        super().__init__(node)
        self.pod_name = node[0]
        self.namespace = namespace
        self.context = context
        self.container = container
        del kwargs

    def _kubectl_base(self) -> List[str]:
        base = ['kubectl']
        if self.context:
            base += ['--context', self.context]
        base += ['-n', self.namespace]
        return base

    def _exec_argv(self, cmd: str, interactive: bool = False) -> List[str]:
        argv = self._kubectl_base() + ['exec']
        if interactive:
            argv.append('-i')
        argv.append(self.pod_name)
        if self.container:
            argv += ['-c', self.container]
        return argv + ['--', 'bash', '-c', cmd]

    def run(self,
            cmd: Union[str, List[str]],
            *,
            require_outputs: bool = False,
            log_path: str = os.devnull,
            stream_logs: bool = True,
            connect_timeout: Optional[int] = None,
            **kwargs: Any) -> Union[int, Tuple[int, str, str]]:
        on_spawn = kwargs.pop('on_spawn', None)
        del connect_timeout, kwargs
        if isinstance(cmd, list):
            cmd = ' '.join(cmd)
        return _run_local(self._exec_argv(cmd), shell=False,
                          require_outputs=require_outputs,
                          log_path=log_path, stream_logs=stream_logs,
                          on_spawn=on_spawn)

    def spawn_spec(self, cmd: str) -> Optional[List[str]]:
        return self._exec_argv(cmd)

    @staticmethod
    def _remote_quote(path: str) -> str:
        """Quote a remote path while keeping leading '~' expandable
        (every framework remote path is '~/...'; quoting the tilde
        would create a literal './~' directory in the pod)."""
        if path == '~':
            return '"$HOME"'
        if path.startswith('~/'):
            return '"$HOME"' + shlex.quote(path[1:])
        return shlex.quote(path)

    @staticmethod
    def _tar_excludes(src: str) -> List[str]:
        """Honor .skyignore/.gitignore on upload (parity with the ssh
        and local runners' exclude behavior)."""
        from skypilot_tpu.data import storage_utils  # pylint: disable=import-outside-toplevel
        excludes = ['--exclude', './.git']
        for rel in storage_utils.get_excluded_files(src):
            excludes += ['--exclude', f'./{rel.rstrip("/")}']
        return excludes

    def rsync(self, source: str, target: str, *, up: bool,
              log_path: str = os.devnull, stream_logs: bool = True) -> None:
        # tar-over-exec: works for files and directories both ways.
        q = self._remote_quote
        if up:
            src = os.path.expanduser(source)
            parent, base = os.path.split(src.rstrip('/'))
            if os.path.isdir(src):
                tar_in = subprocess.Popen(
                    ['tar', '-C', src] + self._tar_excludes(src) +
                    ['-cf', '-', '.'],
                    stdout=subprocess.PIPE)
                untar = self._exec_argv(
                    f'mkdir -p {q(target)} && '
                    f'tar -C {q(target)} -xf -', interactive=True)
            else:
                tar_in = subprocess.Popen(
                    ['tar', '-C', parent or '.', '-cf', '-', base],
                    stdout=subprocess.PIPE)
                dst_dir = os.path.dirname(target) or '.'
                untar = self._exec_argv(
                    f'mkdir -p {q(dst_dir)} && '
                    f'tar -C {q(dst_dir)} -xf - && '
                    f'mv {q(os.path.join(dst_dir, base))} '
                    f'{q(target)} 2>/dev/null || true',
                    interactive=True)
            proc = subprocess.run(untar, stdin=tar_in.stdout, check=False,
                                  capture_output=True, text=True)
            tar_in.wait()
            subprocess_utils.handle_returncode(
                proc.returncode, ' '.join(untar),
                f'Failed to sync up {source} -> {target}', proc.stderr,
                stream_logs)
        else:
            import tarfile
            os.makedirs(os.path.dirname(os.path.expanduser(target)) or '.',
                        exist_ok=True)
            parent = os.path.dirname(source.rstrip('/')) or '.'
            base = os.path.basename(source.rstrip('/'))
            tar_out = self._exec_argv(
                f'tar -C {q(parent)} -cf - {shlex.quote(base)}')
            # Stream the archive straight into tarfile (no full-buffer
            # copy: sync-down may be multi-GB of logs/checkpoints).
            proc = subprocess.Popen(tar_out, stdout=subprocess.PIPE,
                                    stderr=subprocess.PIPE)
            target_dir = os.path.expanduser(target)
            extract_to = (target_dir if os.path.isdir(target_dir)
                          else os.path.dirname(target_dir) or '.')
            try:
                with tarfile.open(fileobj=proc.stdout, mode='r|') as tf:
                    tf.extractall(extract_to)
            except tarfile.TarError:
                pass  # handled via returncode below
            _, stderr = proc.communicate()
            if proc.returncode != 0:
                subprocess_utils.handle_returncode(
                    proc.returncode, ' '.join(tar_out),
                    f'Failed to sync down {source}',
                    stderr.decode(errors='replace'), stream_logs)


class DockerCommandRunner(KubernetesCommandRunner):
    """Runner for local docker containers via `docker exec`.

    Parity: reference backends/local_docker_backend.py +
    docker_utils.py — containers stand in for slice hosts (quick
    local iteration without a cloud).  Inherits the tar-over-exec
    file-transfer machinery; only the exec argv differs.
    """

    def __init__(self, node: Tuple[str, int], **kwargs: Any) -> None:
        CommandRunner.__init__(self, node)
        self.container_name = node[0]
        del kwargs

    def _exec_argv(self, cmd: str, interactive: bool = False) -> List[str]:
        argv = ['docker', 'exec']
        if interactive:
            argv.append('-i')
        return argv + [self.container_name, 'bash', '-c', cmd]
