"""Spawn-time daemon registry: crash-safe orphan reaping.

VERDICT r2 weak #5: pytest session fixtures reap daemons on clean exit,
but a kill -9 of the test runner leaves skylets/controllers alive with
their (deleted) tmp homes.  Fix: every daemon spawn appends a record to
a registry OUTSIDE the per-test/per-user SKYTPU_HOME (a fixed path
under the real user's home, env-overridable); `reap_stale()` runs at
process startup (conftest, skylet start, CLI entry) and kills any
registered daemon whose home directory no longer exists, plus prunes
dead entries.  PID reuse is guarded by recording the process create
time and matching it before killing.

No reference equivalent (the reference leans on Ray's GCS for process
supervision; we are Ray-free by design — SURVEY.md §7(a)).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_ENV_REGISTRY = 'SKYTPU_DAEMON_REGISTRY'


def _registry_path() -> str:
    path = os.environ.get(_ENV_REGISTRY)
    if path:
        return path
    # The REAL user home from passwd — NOT $HOME/expanduser, which the
    # local provisioner points at per-host tmp dirs that vanish with the
    # test run (the registry must outlive every fake home).
    try:
        import pwd  # pylint: disable=import-outside-toplevel
        home = pwd.getpwuid(os.getuid()).pw_dir
    except (ImportError, KeyError):
        home = os.path.expanduser('~')
    return os.path.join(home, '.skytpu_daemon_registry.jsonl')


def _proc_create_time(pid: int) -> Optional[float]:
    try:
        import psutil  # pylint: disable=import-outside-toplevel
        return psutil.Process(pid).create_time()
    except Exception:  # pylint: disable=broad-except
        return None


def register(pid: int, kind: str, home: Optional[str] = None) -> None:
    """Append a spawn record.  Called right after Popen; atomic via
    O_APPEND single-line writes."""
    if home is None:
        from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
        home = common_utils.skytpu_home()
    rec = {
        'pid': pid,
        'kind': kind,
        'home': os.path.expanduser(home),
        'create_time': _proc_create_time(pid),
        'registered_at': time.time(),
    }
    path = _registry_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with _lock(path):
            with open(path, 'a', encoding='utf-8') as f:
                f.write(json.dumps(rec) + '\n')
    except OSError as e:
        logger.debug(f'daemon registry append failed: {e}')


def _lock(path: str):
    """Registry mutations are cross-process (any CLI/test may reap
    while a launch registers): serialize via filelock."""
    import filelock  # pylint: disable=import-outside-toplevel
    return filelock.FileLock(f'{path}.lock', timeout=10)


def _load() -> List[Dict[str, Any]]:
    try:
        with open(_registry_path(), encoding='utf-8') as f:
            out = []
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
            return out
    except OSError:
        return []


def _same_process(rec: Dict[str, Any]) -> bool:
    """The recorded pid still names the process we registered."""
    now_ct = _proc_create_time(rec['pid'])
    then_ct = rec.get('create_time')
    if now_ct is None or then_ct is None:
        # Unverifiable identity: NEVER kill (a reused pid could name an
        # unrelated process); the entry is pruned instead.
        return False
    # Allow sub-second clock fuzz; a reused pid differs by far more.
    return abs(now_ct - then_ct) < 1.0


def _kill_tree(pid: int) -> None:
    try:
        import psutil  # pylint: disable=import-outside-toplevel
        proc = psutil.Process(pid)
        procs = [proc]
        try:
            procs += proc.children(recursive=True)
        except psutil.NoSuchProcess:
            pass
        for p in procs:
            try:
                p.kill()
            except psutil.NoSuchProcess:
                pass
    except Exception:  # pylint: disable=broad-except
        pass


def reap_stale() -> int:
    """Kill registered daemons whose home dir vanished; prune dead
    entries.  Returns the number of daemons killed.  Load + rewrite run
    under the registry lock so a concurrent register() is never lost."""
    path = _registry_path()
    try:
        with _lock(path):
            return _reap_stale_locked(path)
    except OSError as e:
        logger.debug(f'daemon registry reap failed: {e}')
        return 0


def _reap_stale_locked(path: str) -> int:
    records = _load()
    if not records:
        return 0
    killed = 0
    keep: List[Dict[str, Any]] = []
    for rec in records:
        alive = _same_process(rec)
        if not alive:
            continue  # dead: prune silently
        home = rec.get('home') or ''
        if home and not os.path.isdir(home):
            # Its state dir is gone (deleted tmp test home, torn-down
            # cluster dir): the daemon is an orphan by definition.
            logger.info(f'Reaping orphaned {rec.get("kind", "daemon")} '
                        f'pid={rec["pid"]} (home {home!r} vanished).')
            _kill_tree(rec['pid'])
            killed += 1
            continue
        keep.append(rec)
    # Rewrite compacted registry (best-effort; atomic replace).
    try:
        tmp = f'{path}.tmp.{os.getpid()}'
        with open(tmp, 'w', encoding='utf-8') as f:
            for rec in keep:
                f.write(json.dumps(rec) + '\n')
        os.replace(tmp, path)
    except OSError as e:
        logger.debug(f'daemon registry rewrite failed: {e}')
    return killed
