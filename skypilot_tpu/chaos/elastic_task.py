"""The training task the elastic chaos scenarios gang-launch.

Run as ``python -m skypilot_tpu.chaos.elastic_task`` on every rank of a
local-backend cluster.  It is a REAL (tiny, CPU) training run wired
through the framework's elastic machinery — ElasticTrainer, the async
checkpoint manager, the checkpoint contract — so the scenario verifies
the actual resize/restore path, not a marker-file pantomime:

- Rank 0 drives the slice's mesh (2 virtual CPU devices per live host,
  forced via XLA_FLAGS before jax imports) and checkpoints through the
  contract dir (``SKYTPU_CHECKPOINT_DIR``).  Losses append to a shared
  CSV so the scenario can assert loss continuity across resizes: the
  per-step batch is a pure function of the step number, so recomputed
  overlap steps must reproduce the first run's losses.
- Ranks != 0 are lightweight placeholders (no jax import): they wait
  for rank 0's done marker, standing in for the hosts a preemption
  reclaims.

Segment logic, inferred from the gang env + checkpoint state:

    fresh (no checkpoint, full gang)   warm up fast so checkpoints
                                       exist early, then train slowly
                                       until the chaos eviction kills
                                       the gang mid-step
    shrunk (checkpoint, gang < full)   sharded-restore onto the small
                                       mesh, train FINAL_STEPS; in
                                       'shrink' mode finish (SUCCEEDED),
                                       in 'roundtrip' mode park and
                                       await the expansion eviction
    expanded (checkpoint, full gang)   restore, train FINAL_STEPS,
                                       finish

Environment (set by chaos/scenarios.py via task envs):
    SKYTPU_ELASTIC_FULL_HOSTS   full slice size (hosts)
    SKYTPU_ELASTIC_MODE         'shrink' | 'roundtrip'
    SKYTPU_ELASTIC_LOSS_LOG     shared CSV path: num_hosts,step,loss
    SKYTPU_ELASTIC_FINAL_STEPS  steps after the final resume (default 4)
"""
from __future__ import annotations

import os
import sys
import time

_CHIPS_PER_EMULATED_HOST = 2


def _rank0_main(num_hosts: int, full_hosts: int, mode: str,
                loss_log: str, final_steps: int, done_marker: str) -> int:
    # Device count must be pinned BEFORE jax imports: the mesh emulates
    # this slice's chips — 2 per live host — so a shrunken gang really
    # does rebuild a smaller mesh.
    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ['XLA_FLAGS'] = (
        f'--xla_force_host_platform_device_count='
        f'{_CHIPS_PER_EMULATED_HOST * num_hosts}')
    import jax  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.data import checkpoints  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.models import configs  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.models.elastic import ElasticTrainer  # pylint: disable=import-outside-toplevel

    ckpt_dir = checkpoints.checkpoint_dir()
    assert ckpt_dir, 'elastic task needs the checkpoint contract'
    trainer = ElasticTrainer(configs.get_config('tiny'),
                             checkpoint_dir=ckpt_dir,
                             batch_size=8, seq_len=32,
                             save_interval_steps=2,
                             devices=jax.devices())
    resumed = trainer.resumed_from_checkpoint

    def train_and_log(num_steps: int, step_sleep_s: float = 0.0) -> None:
        # One step at a time, appending the loss IMMEDIATELY: the
        # eviction kills this process mid-run, and the scenario's
        # loss-continuity check needs every completed step on disk.
        for _ in range(num_steps):
            for step, loss in trainer.train_steps(1):
                with open(loss_log, 'a', encoding='utf-8') as f:
                    f.write(f'{num_hosts},{step},{loss:.6f}\n')
            if step_sleep_s:
                time.sleep(step_sleep_s)
        print(f'[elastic_task] hosts={num_hosts} trained to step '
              f'{trainer.step}', flush=True)

    if not resumed:
        # Fresh full-size run: warm up fast so the eviction (timed by
        # the scenario's fault plan) always lands after checkpoints
        # exist, then train slowly until it kills us mid-step.
        train_and_log(6)
        train_and_log(200, step_sleep_s=0.4)
        # Backstop (chaos never came): finish cleanly so a hung plan
        # shows up as a missing gang_resize, not a wedged job.
        trainer.close()
        _touch(done_marker)
        return 0

    if num_hosts < full_hosts and mode == 'roundtrip':
        # Shrunk and awaiting expansion: make some progress on the
        # small mesh, then park — the capacity-returns eviction
        # relaunches us at full size.
        train_and_log(final_steps)
        trainer.close()
        time.sleep(300)
        return 0

    # Final segment: shrunk (mode 'shrink') or expanded back to full.
    train_and_log(final_steps)
    trainer.close()
    _touch(done_marker)
    return 0


def _placeholder_main(done_marker: str) -> int:
    """Ranks != 0: hold the host until rank 0 finishes (or the chaos
    eviction reclaims this host)."""
    deadline = time.time() + 600
    while time.time() < deadline:
        if os.path.exists(done_marker):
            return 0
        time.sleep(0.25)
    return 1


def _touch(path: str) -> None:
    with open(path, 'w', encoding='utf-8') as f:
        f.write('done\n')


def main() -> int:
    rank = int(os.environ.get('SKYTPU_HOST_RANK', '0'))
    num_hosts = int(os.environ.get('SKYTPU_NUM_HOSTS', '1'))
    full_hosts = int(os.environ.get('SKYTPU_ELASTIC_FULL_HOSTS',
                                    str(num_hosts)))
    mode = os.environ.get('SKYTPU_ELASTIC_MODE', 'shrink')
    loss_log = os.environ.get('SKYTPU_ELASTIC_LOSS_LOG')
    final_steps = int(os.environ.get('SKYTPU_ELASTIC_FINAL_STEPS', '4'))
    assert loss_log, 'SKYTPU_ELASTIC_LOSS_LOG must be set'
    done_marker = loss_log + '.done'
    if rank != 0:
        return _placeholder_main(done_marker)
    return _rank0_main(num_hosts, full_hosts, mode, loss_log,
                       final_steps, done_marker)


if __name__ == '__main__':
    sys.exit(main())
