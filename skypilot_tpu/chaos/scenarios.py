"""End-to-end chaos scenarios: launch → fault → recover, journal-verified.

Each scenario arms a seeded :class:`~skypilot_tpu.chaos.faults.FaultPlan`
(via ``SKYTPU_CHAOS_PLAN``, so emulated-host subprocesses inherit it),
drives a real flow on the local backend — the same provisioner /
backend / gang supervisor / jobs controller / serve code paths that run
against clouds — and then replays the flight-recorder journals through
:mod:`~skypilot_tpu.chaos.invariants`.  A scenario passes iff every
invariant holds AND its scenario-specific expectations match.

Scenarios (CLI: ``sky chaos list`` / ``sky chaos run <name>``):

- ``provision_failover``   zone-a stockout → failover provisions zone-b
- ``preemption_recovery``  task cluster evicted mid-job → controller
                           detects, recovers, job still succeeds
- ``rank_crash``           one rank of a 4-host gang dies → fail-fast
                           abort covers every live rank
- ``queued_stall``         queued-resource capacity never granted →
                           wait times out with a terminal verdict
- ``serve_replica_flap``   readiness probes fail transiently → replica
                           flaps NOT_READY and returns to READY; the
                           router re-pins prefix affinity off a dead
                           replica
- ``drain_under_load``     scale-down + rolling replacement mid-
                           traffic → zero non-2xx, no request routed
                           to a retired replica, hot prefix pages
                           handed to the surviving sibling
- ``workload_flip_morph``  all-prefill burst flips all-decode mid-
                           traffic → the prefill replica LIVE-morphs
                           into the decode pool (no restart), zero
                           non-2xx, ITL p99 bounded, the morph
                           journaled and replay-verified
- ``controller_crash_recovery`` controller killed/restarted mid-
                           service (first new tick chaos-wedged) →
                           fleet re-adopted from serve_state, warm-
                           started autoscaler, zero churn on the first
                           real reconcile pass
- ``replica_rank_death``   one rank of a 2-host slice replica dies →
                           the replica fails AS A UNIT (503 +
                           slice.degraded), the LB re-routes with zero
                           lost requests, the controller probe retires
                           it (``replica_rank_death_rebuild`` adds the
                           full replacement roundtrip)
- ``handoff_fallback``     KV handoff import denied → the router falls
                           back to local prefill on the decode
                           replica; journal proves no request was lost
                           or double-executed
- ``error_spike``          a rank death floods the replica's WARN/
                           ERROR logs → the fleet log plane journals
                           log_error_spike_start; once the fleet
                           quiets the spike terminates (replay proves
                           every spike start has its end)
- ``page_pool_exhaustion`` KV page allocations denied → the batching
                           engine backpressures (429/Retry-After)
                           instead of erroring, recovers when the
                           window passes, and the journal proves every
                           allocated page was freed
- ``router_instance_death`` one router of a two-router tier killed
                           mid-traffic → the hash ring re-homes its
                           keys, the shared brain store keeps every
                           pin, zero non-2xx, no QoS inversion
- ``region_loss_failover`` every replica of the router-local region
                           dies abruptly → region-aware dispatch
                           fails over cross-region with zero lost
                           requests
- ``elastic_shrink``       mid-step partial preemption → ELASTIC
                           recovery shrinks the gang to the survivor,
                           sharded-restores onto the smaller mesh, and
                           resumes with loss continuity
- ``elastic_expand``       shrink → capacity returns → expand round
                           trip: the resumed job is relaunched at full
                           size, progress preserved throughout
- ``checkpoint_storm``     checkpoint-write fault storm → saves retry
                           with backoff off the step path; training
                           never stalls past the in-flight bound
- ``batch_resume``         batch-infer driver killed mid-commit + a
                           replica killed mid-shard + a live weight
                           swap → a fresh driver resumes off the shard
                           ledger and completes with exactly-once
                           outputs

Determinism: the fault sequence (site, effect, per-site call number) is
a pure function of plan + seed over the driven call sequence; the
scenario result carries it so the same ``--seed`` can be diffed run
over run.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import faults as faults_lib
from skypilot_tpu.chaos import injector
from skypilot_tpu.chaos import invariants
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.serve import http_protocol

logger = sky_logging.init_logger(__name__)

_WAIT_JOB_TIMEOUT_SECONDS = 120.0


@dataclasses.dataclass
class ScenarioResult:
    name: str
    seed: int
    violations: List[str]
    # (site, effect, per-site call number, fault index) — deterministic
    # for a given plan+seed; environmental ctx is deliberately excluded.
    fault_sequence: List[Dict[str, Any]]
    events: List[Dict[str, Any]]
    details: Dict[str, Any]

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        status = 'PASS' if self.ok else 'FAIL'
        return (f'{self.name} (seed {self.seed}): {status} — '
                f'{len(self.fault_sequence)} fault(s) injected, '
                f'{len(self.violations)} violation(s)')


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    run: Callable[[int], ScenarioResult]


SCENARIOS: Dict[str, Scenario] = {}


def _register(name: str, description: str):

    def deco(fn):
        SCENARIOS[name] = Scenario(name, description, fn)
        return fn

    return deco


def run_scenario(name: str, seed: int = 0,
                 export_trace: Optional[str] = None) -> ScenarioResult:
    """Run one scenario; optionally export its merged journal as a
    Chrome trace for post-mortem."""
    scenario = SCENARIOS.get(name)
    if scenario is None:
        raise ValueError(f'Unknown scenario {name!r}; have '
                         f'{sorted(SCENARIOS)}')
    result = scenario.run(seed)
    if export_trace:
        events_lib.export_chrome_trace(result.events, export_trace)
    return result


# ----------------------------------------------------------- shared helpers


@contextlib.contextmanager
def _armed(plan: faults_lib.FaultPlan) -> Iterator[None]:
    """Arm via the environment (inherited by emulated-host subprocesses)
    and leave nothing armed afterwards."""
    prior = os.environ.get(faults_lib.PLAN_ENV_VAR)
    os.environ[faults_lib.PLAN_ENV_VAR] = plan.to_json()
    injector.disarm()  # drop any stale cached plan
    try:
        yield
    finally:
        if prior is None:
            os.environ.pop(faults_lib.PLAN_ENV_VAR, None)
        else:
            os.environ[faults_lib.PLAN_ENV_VAR] = prior
        injector.disarm()


@contextlib.contextmanager
def _local_cloud_enabled() -> Iterator[None]:
    from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
    prior = global_user_state.get_enabled_clouds()
    global_user_state.set_enabled_clouds(['local'])
    try:
        yield
    finally:
        if prior and prior != ['local']:
            global_user_state.set_enabled_clouds(prior)


@contextlib.contextmanager
def _two_zone_local() -> Iterator[None]:
    """Give the Local cloud two zones so the failover loop has somewhere
    to go (the real cloud path; zones are synthetic)."""
    from skypilot_tpu.clouds import cloud as cloud_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.clouds import local as local_cloud  # pylint: disable=import-outside-toplevel

    def regions(self, resources):
        del self, resources
        return [cloud_lib.Region('local').set_zones(
            [cloud_lib.Zone('zone-a', 'local'),
             cloud_lib.Zone('zone-b', 'local')])]

    saved_regions = local_cloud.Local.regions_with_offering
    saved_validate = local_cloud.Local.validate_region_zone
    local_cloud.Local.regions_with_offering = regions
    local_cloud.Local.validate_region_zone = (
        lambda self, region, zone: (region, zone))
    try:
        yield
    finally:
        local_cloud.Local.regions_with_offering = saved_regions
        local_cloud.Local.validate_region_zone = saved_validate


def _down(cluster_name: str) -> None:
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import exceptions  # pylint: disable=import-outside-toplevel
    try:
        core.down(cluster_name)
    except (exceptions.SkyTpuError, ValueError):
        pass


def _wait_job(cluster: str, job_id: int,
              timeout: float = _WAIT_JOB_TIMEOUT_SECONDS) -> str:
    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    deadline = time.time() + timeout
    value = None
    while time.time() < deadline:
        value = sky.job_status(cluster, [job_id]).get(str(job_id))
        if value in ('SUCCEEDED', 'FAILED', 'FAILED_SETUP',
                     'FAILED_DRIVER', 'CANCELLED'):
            return value
        time.sleep(0.5)
    raise TimeoutError(f'Job {job_id} on {cluster} did not finish '
                       f'(last status: {value})')


def _since(journal: events_lib.EventJournal,
           t0: float) -> List[Dict[str, Any]]:
    """Journal events appended since t0 (journals persist across runs of
    the same scenario/seed; the window keeps replays scoped)."""
    return [e for e in journal.read() if e.get('ts', 0.0) >= t0]


def _fault_sequence(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [{'site': e.get('site'), 'effect': e.get('effect'),
             'call': e.get('call')}
            for e in events if e.get('event') == 'chaos_fault_injected']


def _finish(name: str, seed: int, t0: float,
            scoped_events: List[Dict[str, Any]],
            invariant_names: List[str],
            extra_violations: List[str],
            details: Dict[str, Any]) -> ScenarioResult:
    chaos_events = _since(injector.chaos_journal(), t0)
    merged = invariants.merge(scoped_events, chaos_events)
    violations = invariants.check(merged, invariant_names)
    violations.extend(extra_violations)
    return ScenarioResult(name=name, seed=seed, violations=violations,
                          fault_sequence=_fault_sequence(merged),
                          events=merged, details=details)


def _expect(condition: bool, message: str,
            violations: List[str]) -> None:
    if not condition:
        violations.append(f'expectation: {message}')


# --------------------------------------------------------------- scenarios


@_register(
    'provision_failover',
    'zone-a provision stockout -> failover loop lands the slice in '
    'zone-b; journal shows fail->ok attempts and no excluded-zone retry')
def provision_failover(seed: int) -> ScenarioResult:
    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    plan = faults_lib.FaultPlan(seed=seed, name='provision_failover',
                                faults=[faults_lib.Fault(
                                    site='provision.create',
                                    effect='raise',
                                    error='ProvisionError',
                                    message='chaos: zone-a stockout',
                                    where={'zone': 'zone-a'})])
    cluster = f'chaos-fo-{seed}'
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {'cluster': cluster}
    with _local_cloud_enabled(), _two_zone_local(), _armed(plan):
        try:
            task = sky.Task(name='chaos-fo', run='echo CHAOS_FAILOVER_OK')
            task.set_resources(sky.Resources(cloud='local'))
            job_id = sky.launch(task, cluster_name=cluster,
                                stream_logs=False, detach_run=True)
            details['job_status'] = _wait_job(cluster, job_id)
        finally:
            cluster_events = _since(events_lib.cluster_journal(cluster),
                                    t0)
            _down(cluster)

    _expect(details.get('job_status') == 'SUCCEEDED',
            f'job SUCCEEDED after failover '
            f'(got {details.get("job_status")})', extra)
    attempts = [e for e in cluster_events
                if e.get('event') == 'provision_attempt_end']
    details['attempts'] = [(a.get('zone'), a.get('status'))
                           for a in attempts]
    _expect(len(attempts) == 2, f'exactly two provision attempts '
            f'(got {details["attempts"]})', extra)
    if len(attempts) == 2:
        _expect(attempts[0].get('zone') == 'zone-a' and
                attempts[0].get('status') == 'fail',
                f'first attempt fails in zone-a (got {details["attempts"]})',
                extra)
        _expect(attempts[1].get('zone') == 'zone-b' and
                attempts[1].get('status') == 'ok',
                f'second attempt succeeds in zone-b '
                f'(got {details["attempts"]})', extra)
    return _finish('provision_failover', seed, t0, cluster_events,
                   ['no_excluded_zone_retry', 'spans_closed'],
                   extra, details)


@_register(
    'preemption_recovery',
    'task cluster evicted mid-job (preempt effect) -> controller '
    'detects the preemption, recovers, and the managed job succeeds')
def preemption_recovery(seed: int) -> ScenarioResult:
    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.jobs import controller as controller_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.jobs import state as jobs_state  # pylint: disable=import-outside-toplevel

    plan = faults_lib.FaultPlan(seed=seed, name='preemption_recovery',
                                faults=[faults_lib.Fault(
                                    site='jobs.status_poll',
                                    effect='preempt',
                                    nth=2, max_times=1)])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    os.makedirs(events_lib.journal_root(), exist_ok=True)
    marker = os.path.join(
        events_lib.journal_root(), f'.chaos-preempt-marker-{seed}-{t0:.0f}')
    # First run parks in a long sleep after dropping the marker; the
    # recovered run finds the marker and exits immediately (the
    # checkpoint-resume contract in miniature).
    run_cmd = (f'if [ -f {marker} ]; then echo CHAOS_RESUMED; '
               f'else touch {marker} && sleep 30; fi')
    poll_env = {'SKYTPU_JOB_STATUS_CHECK_GAP': '0.4',
                'SKYTPU_JOB_STARTED_CHECK_GAP': '0.4'}
    saved_env = {k: os.environ.get(k) for k in poll_env}
    os.environ.update(poll_env)
    try:
        with _local_cloud_enabled(), _armed(plan):
            task = sky.Task(name='chaos-preempt', run=run_cmd)
            task.set_resources(sky.Resources(cloud='local'))
            job_id = _submit_managed(task, 'chaos-preempt')
            details['job_id'] = job_id
            controller_lib.JobsController(
                job_id, jobs_state.get_job_records(job_id)[0]
                ['dag_yaml_path']).run()
    finally:
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
        try:
            os.remove(marker)
        except OSError:
            pass

    record = jobs_state.get_job_records(details['job_id'])[0]
    details['status'] = record['status']
    details['recovery_count'] = record['recovery_count']
    details['last_recovery_reason'] = record['last_recovery_reason']
    job_events = _since(events_lib.job_journal(details['job_id']), t0)
    _expect(record['status'] == 'SUCCEEDED',
            f'managed job SUCCEEDED after recovery '
            f'(got {record["status"]})', extra)
    _expect(record['recovery_count'] >= 1,
            'recovery_count >= 1 after the injected eviction', extra)
    names = [e.get('event') for e in job_events]
    _expect('preemption_detected' in names,
            'controller journaled preemption_detected', extra)
    recovery_ends = [e for e in job_events
                     if e.get('event') == 'recovery_end']
    _expect(any(e.get('status') == 'ok' for e in recovery_ends),
            'a recovery_end with status=ok was journaled', extra)
    return _finish('preemption_recovery', seed, t0, job_events,
                   ['recovery_liveness'], extra, details)


def _submit_managed(task, name: str) -> int:
    """Submit a managed job without spawning the controller daemon (the
    scenario runs the controller inline for determinism)."""
    from skypilot_tpu.jobs import core as jobs_core  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.jobs import state as jobs_state  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import dag_utils  # pylint: disable=import-outside-toplevel
    dag = dag_utils.convert_entrypoint_to_dag(task)
    job_id = jobs_state.allocate_job_id(name)
    yaml_path = os.path.join(jobs_core._dag_yaml_dir(),  # pylint: disable=protected-access
                             f'{name}-{job_id}.yaml')
    dag_utils.dump_chain_dag_to_yaml(dag, yaml_path)
    jobs_state.submit_job(job_id, name, yaml_path,
                          [t.name or f'task-{i}'
                           for i, t in enumerate(dag.tasks)])
    jobs_state.set_status(job_id, 0,
                          jobs_state.ManagedJobStatus.SUBMITTED)
    return job_id


@_register(
    'rank_crash',
    'rank 1 of a 4-host gang dies at exec -> fail-fast abort terminates '
    'every live rank; no rank is left running in a dead collective')
def rank_crash(seed: int) -> ScenarioResult:
    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    plan = faults_lib.FaultPlan(seed=seed, name='rank_crash',
                                faults=[faults_lib.Fault(
                                    site='gang.rank_exec',
                                    effect='raise',
                                    where={'rank': 1},
                                    max_times=1)])
    cluster = f'chaos-rank-{seed}'
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {'cluster': cluster}
    with _local_cloud_enabled(), _armed(plan):
        try:
            task = sky.Task(name='chaos-rank', run='sleep 30')
            task.set_resources(
                sky.Resources(cloud='local', accelerators='tpu-v5e-16'))
            job_id = sky.launch(task, cluster_name=cluster,
                                stream_logs=False, detach_run=True)
            details['job_status'] = _wait_job(cluster, job_id)
            gang_events = _since(
                events_lib.cluster_job_journal(job_id), t0)
        finally:
            _down(cluster)

    _expect(details.get('job_status') == 'FAILED',
            f'all-or-nothing gang FAILED (got {details.get("job_status")})',
            extra)
    names = [e.get('event') for e in gang_events]
    _expect('gang_abort' in names, 'gang_abort was journaled', extra)
    gang_end = next((e for e in gang_events
                     if e.get('event') == 'gang_end'), None)
    _expect(gang_end is not None and gang_end.get('status') == 'fail',
            'gang_end has status=fail', extra)
    aborts = [e for e in gang_events if e.get('event') == 'gang_abort']
    if aborts:
        details['failed_rank'] = aborts[0].get('failed_rank')
        details['victims'] = aborts[0].get('victims')
        _expect(aborts[0].get('failed_rank') == 1,
                f'rank 1 is the failed rank '
                f'(got {aborts[0].get("failed_rank")})', extra)
    return _finish('rank_crash', seed, t0, gang_events,
                   ['gang_abort_coverage'], extra, details)


@_register(
    'queued_stall',
    'queued-resource capacity never granted (deny effect) -> the wait '
    'loop reaches its deadline and journals a terminal timeout verdict')
def queued_stall(seed: int) -> ScenarioResult:
    from skypilot_tpu.provision import provisioner as provisioner_lib  # pylint: disable=import-outside-toplevel
    plan = faults_lib.FaultPlan(seed=seed, name='queued_stall',
                                faults=[faults_lib.Fault(
                                    site='queued_resource.poll',
                                    effect='deny')])
    cluster = f'chaos-queued-{seed}'
    t0 = time.time()
    extra: List[str] = []
    with _armed(plan):
        granted = provisioner_lib.wait_for_queued_capacity(
            'local', cluster, timeout=1.2)
    cluster_events = _since(events_lib.cluster_journal(cluster), t0)
    details: Dict[str, Any] = {'cluster': cluster, 'granted': granted}
    _expect(granted is False,
            'capacity is NOT granted while every poll is denied', extra)
    end = next((e for e in cluster_events
                if e.get('event') == 'queued_wait_end'), None)
    _expect(end is not None and end.get('status') == 'timeout',
            f'queued_wait_end status=timeout '
            f'(got {end.get("status") if end else None})', extra)
    if end is not None:
        details['wait_s'] = end.get('wait_s')
        details['polls'] = end.get('polls')
        _expect((end.get('wait_s') or 0) >= 1.0,
                'the wait actually lasted to the deadline', extra)
    return _finish('queued_stall', seed, t0, cluster_events,
                   ['queued_wait_terminal'], extra, details)


# ------------------------------------------------------ elastic scenarios


_ELASTIC_FULL_HOSTS = 2
# Poll gaps are the scenario clock: the partial eviction fires on the
# 2nd status poll, which must land AFTER the task's warmup checkpoints
# exist (jax import ~2-5s + 6 fast steps), hence seconds-scale gaps.
_ELASTIC_POLL_GAP = '5.0'
_ELASTIC_STARTED_GAP = '6.0'
# "Resume within N steps": the resumed segment may recompute at most
# the save interval (2) plus one in-flight save plus slack.
_ELASTIC_MAX_LOST_STEPS = 6


def _read_loss_log(path: str) -> List[Dict[str, Any]]:
    rows = []
    try:
        with open(path, encoding='utf-8') as f:
            for line in f:
                hosts, step, loss = line.strip().split(',')
                rows.append({'hosts': int(hosts), 'step': int(step),
                             'loss': float(loss)})
    except OSError:
        pass
    return rows


def _check_loss_continuity(rows: List[Dict[str, Any]],
                           extra: List[str],
                           details: Dict[str, Any]) -> None:
    """The loss-continuity contract: the batch at step k is a pure
    function of k, so steps recomputed after a resize must reproduce
    the pre-resize losses — a sharded restore that lost or mangled
    state shows up as divergence here.

    Rows are in append order; a change in the gang size between
    consecutive rows marks a resize boundary.  At every boundary the
    resumed segment must continue the run (first step <= killed step +
    1) within the lost-work budget (save interval + one in-flight
    save + slack)."""
    segments: List[List[Dict[str, Any]]] = []
    for row in rows:
        if not segments or segments[-1][-1]['hosts'] != row['hosts']:
            segments.append([])
        segments[-1].append(row)
    details['segments'] = [
        (seg[0]['hosts'], seg[0]['step'], seg[-1]['step'])
        for seg in segments]
    _expect(len(segments) >= 2,
            f'the loss log shows a resize (segments: '
            f'{details["segments"]})', extra)
    _expect(any(seg[0]['hosts'] < _ELASTIC_FULL_HOSTS
                for seg in segments),
            'some segment ran on the shrunken gang', extra)
    for prev, cur in zip(segments, segments[1:]):
        killed_at = prev[-1]['step']
        resumed_at = cur[0]['step']
        _expect(resumed_at <= killed_at + 1,
                f'resume continues the run (resumed {resumed_at} '
                f'after step {killed_at})', extra)
        _expect(killed_at - resumed_at <= _ELASTIC_MAX_LOST_STEPS,
                f'resume within {_ELASTIC_MAX_LOST_STEPS} steps '
                f'(lost {killed_at - resumed_at})', extra)
    by_step: Dict[int, List[float]] = {}
    for r in rows:
        by_step.setdefault(r['step'], []).append(r['loss'])
    overlap = {s: ls for s, ls in by_step.items() if len(ls) > 1}
    details['overlap_steps'] = sorted(overlap)
    if overlap:
        max_div = max(max(ls) - min(ls) for ls in overlap.values())
        details['max_loss_divergence'] = max_div
        _expect(max_div < 1e-3,
                f'no loss divergence on recomputed steps '
                f'(max {max_div:.2e})', extra)


def _run_elastic(name: str, seed: int, mode: str,
                 faults: List[faults_lib.Fault],
                 expect_expand: bool) -> ScenarioResult:
    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.jobs import controller as controller_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.jobs import state as jobs_state  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel

    plan = faults_lib.FaultPlan(seed=seed, name=name, faults=faults)
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    workdir = os.path.join(common_utils.skytpu_home(),
                           f'chaos-{name}-{seed}-{t0:.0f}')
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, 'ckpt')
    loss_log = os.path.join(workdir, 'loss.csv')
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    run_cmd = (f'PYTHONPATH={repo_root}:$PYTHONPATH '
               f'{sys.executable} -u -m skypilot_tpu.chaos.elastic_task')
    poll_env = {'SKYTPU_JOB_STATUS_CHECK_GAP': _ELASTIC_POLL_GAP,
                'SKYTPU_JOB_STARTED_CHECK_GAP': _ELASTIC_STARTED_GAP}
    saved_env = {k: os.environ.get(k) for k in poll_env}
    os.environ.update(poll_env)
    cluster = None
    try:
        with _local_cloud_enabled(), _armed(plan):
            task = sky.Task(
                name=f'el-{mode}', num_nodes=_ELASTIC_FULL_HOSTS,
                run=run_cmd, checkpoint_dir=ckpt_dir,
                envs={
                    'SKYTPU_ELASTIC_FULL_HOSTS':
                        str(_ELASTIC_FULL_HOSTS),
                    'SKYTPU_ELASTIC_MODE': mode,
                    'SKYTPU_ELASTIC_LOSS_LOG': loss_log,
                })
            task.set_resources(
                sky.Resources(cloud='local', job_recovery='ELASTIC'))
            job_id = _submit_managed(task, name)
            details['job_id'] = job_id
            cluster = f'el-{mode}-{job_id}-0'
            controller_lib.JobsController(
                job_id, jobs_state.get_job_records(job_id)[0]
                ['dag_yaml_path']).run()
    finally:
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
        if cluster is not None:
            _down(cluster)

    record = jobs_state.get_job_records(details['job_id'])[0]
    details['status'] = record['status']
    details['recovery_count'] = record['recovery_count']
    details['last_recovery_reason'] = record['last_recovery_reason']
    job_events = _since(events_lib.job_journal(details['job_id']), t0)
    training_events = _since(events_lib.training_journal(), t0)

    _expect(record['status'] == 'SUCCEEDED',
            f'managed job SUCCEEDED through the resize(s) '
            f'(got {record["status"]})', extra)
    resizes = [e for e in job_events if e.get('event') == 'gang_resize']
    details['resizes'] = [(e.get('from'), e.get('to'),
                           e.get('direction')) for e in resizes]
    shrinks = [e for e in resizes if e.get('direction') == 'shrink']
    _expect(bool(shrinks), 'a gang_resize shrink was journaled', extra)
    if shrinks:
        _expect(shrinks[0].get('from') == _ELASTIC_FULL_HOSTS and
                shrinks[0].get('to') == _ELASTIC_FULL_HOSTS - 1,
                f'shrink resized {_ELASTIC_FULL_HOSTS}→'
                f'{_ELASTIC_FULL_HOSTS - 1} '
                f'(got {details["resizes"]})', extra)
    resumes = [e for e in training_events
               if e.get('event') == 'train_resume']
    details['resumes'] = [(e.get('step'), e.get('devices'),
                           e.get('restored')) for e in resumes]
    _expect(any(e.get('restored') for e in resumes),
            'a sharded restore onto the rebuilt mesh was journaled '
            f'(train_resume restored=True; got {details["resumes"]})',
            extra)
    if expect_expand:
        expands = [e for e in resizes
                   if e.get('direction') == 'expand']
        _expect(bool(expands), 'a gang_resize expand was journaled',
                extra)
        _expect(record['recovery_count'] >= 2,
                'two recoveries (shrink, then expand)', extra)
        _expect(record['last_recovery_reason'] ==
                f'elastic_expand({_ELASTIC_FULL_HOSTS - 1}→'
                f'{_ELASTIC_FULL_HOSTS})',
                f'last_recovery_reason records the expand '
                f'(got {record["last_recovery_reason"]!r})', extra)
    else:
        _expect(record['last_recovery_reason'] ==
                f'elastic_shrink({_ELASTIC_FULL_HOSTS}→'
                f'{_ELASTIC_FULL_HOSTS - 1})',
                f'last_recovery_reason records the shrink '
                f'(got {record["last_recovery_reason"]!r})', extra)
    _check_loss_continuity(_read_loss_log(loss_log), extra, details)

    # checkpoint_liveness is deliberately NOT applied here: the
    # eviction may kill the writer thread mid-save, legitimately
    # leaving one checkpoint_save_start unterminated (same caveat as
    # spans_closed for crashed processes).
    scoped = invariants.merge(job_events, training_events)
    return _finish(name, seed, t0, scoped,
                   ['recovery_liveness', 'resize_monotone_steps'],
                   extra, details)


@_register(
    'elastic_shrink',
    'mid-step partial preemption (1 of 2 hosts evicted) -> ELASTIC '
    'recovery shrinks the gang to the survivor, sharded-restores onto '
    'the smaller mesh, and resumes within the save interval with loss '
    'continuity')
def elastic_shrink(seed: int) -> ScenarioResult:
    return _run_elastic(
        'elastic_shrink', seed, mode='shrink',
        faults=[faults_lib.Fault(site='jobs.status_poll',
                                 effect='preempt', ranks=[1],
                                 nth=2, max_times=1)],
        expect_expand=False)


@_register(
    'elastic_expand',
    'shrink -> capacity returns -> expand round trip: a partial '
    'eviction shrinks the gang, a later full eviction (capacity '
    'returning) relaunches at full size, progress preserved end to end')
def elastic_expand(seed: int) -> ScenarioResult:
    return _run_elastic(
        'elastic_expand', seed, mode='roundtrip',
        faults=[
            faults_lib.Fault(site='jobs.status_poll', effect='preempt',
                             ranks=[1], nth=2, max_times=1),
            faults_lib.Fault(site='jobs.status_poll', effect='preempt',
                             nth=6, max_times=1),
        ],
        expect_expand=True)


@_register(
    'checkpoint_storm',
    'checkpoint-write fault storm -> every save retries with backoff '
    'off the step path, training never stalls past the in-flight '
    'bound, and the journal shows the retries')
def checkpoint_storm(seed: int) -> ScenarioResult:
    import numpy as np  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.data import checkpoints  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel

    # Per-site call counter semantics make the storm deterministic:
    # save 1 fails its 1st+2nd write attempts (calls 1,2), save 2 its
    # 1st (call 4), save 4 its 1st (call 7); everything else succeeds.
    plan = faults_lib.FaultPlan(seed=seed, name='checkpoint_storm',
                                faults=[faults_lib.Fault(
                                    site='checkpoint.save',
                                    effect='raise', error='OSError',
                                    message='chaos: bucket write flake',
                                    nth=[1, 2, 4, 7])])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    workdir = os.path.join(common_utils.skytpu_home(),
                           f'chaos-ckpt-storm-{seed}-{t0:.0f}')
    journal = events_lib.training_journal()
    num_steps = 5
    state = {'w': np.arange(1024, dtype=np.float32)}
    step_seconds: List[float] = []
    with _armed(plan):
        mgr = checkpoints.AsyncCheckpointManager(
            workdir, save_interval_steps=1, max_in_flight=1,
            max_retries=3, retry_backoff_s=0.02, journal=journal)
        for step in range(num_steps):
            t_step = time.monotonic()
            state = {'w': state['w'] + 1.0}  # the "train step"
            mgr.save(step, state)
            step_seconds.append(time.monotonic() - t_step)
        mgr.close()

    training_events = _since(journal, t0)
    ends = [e for e in training_events
            if e.get('event') == 'checkpoint_save_end']
    details['saves'] = [(e.get('step'), e.get('status'),
                         e.get('attempts')) for e in ends]
    details['blocked_seconds'] = round(mgr.blocked_seconds, 6)
    details['max_step_seconds'] = round(max(step_seconds), 6)
    _expect(len(ends) == num_steps,
            f'{num_steps} saves reached a terminal status '
            f'(got {len(ends)})', extra)
    _expect(all(e.get('status') == 'ok' for e in ends),
            f'every save eventually succeeded (got {details["saves"]})',
            extra)
    _expect(any((e.get('attempts') or 0) > 1 for e in ends),
            'the journal shows retries (attempts > 1)', extra)
    _expect(mgr.latest_step() == num_steps - 1,
            f'newest checkpoint is step {num_steps - 1} '
            f'(got {mgr.latest_step()})', extra)
    # Never stalls past the in-flight bound: a step waits at most for
    # ONE in-flight save (not the whole storm's retries serially).
    save_wall = sum(float(e.get('duration_s') or 0) for e in ends)
    _expect(details['max_step_seconds'] <= save_wall + 1.0,
            f'no step stalled past the in-flight bound '
            f'(max step {details["max_step_seconds"]}s vs total save '
            f'wall {round(save_wall, 3)}s)', extra)
    return _finish('checkpoint_storm', seed, t0, training_events,
                   ['checkpoint_liveness'], extra, details)


@_register(
    'page_pool_exhaustion',
    'KV page-pool allocation denied (deny effect) -> the batching '
    'engine degrades to admission backpressure (QueueFull/429 + '
    'Retry-After), never an engine failure; once the denial window '
    'passes every queued request completes, and the journal proves '
    'every allocated page was freed')
def page_pool_exhaustion(seed: int) -> ScenarioResult:
    import flax.linen as nn  # pylint: disable=import-outside-toplevel
    import jax  # pylint: disable=import-outside-toplevel
    import jax.numpy as jnp  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.models import configs  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.models.transformer import Transformer  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import batching_engine  # pylint: disable=import-outside-toplevel

    # Deny the first page allocations for a wall-clock window: during
    # it NOTHING can be admitted, so the bounded queue fills and new
    # submits must get the 429 class; afterwards the engine recovers
    # on its own.
    plan = faults_lib.FaultPlan(
        seed=seed, name='page_pool_exhaustion',
        faults=[faults_lib.Fault(site='serve.page_pool',
                                 effect='deny', until_s=1.0)])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    cfg = configs.get_config('tiny')
    params = nn.meta.unbox(Transformer(cfg).init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))
        ['params'])
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))
    with _armed(plan):
        eng = batching_engine.ContinuousBatchingEngine(
            cfg, params, max_len=32, slots=2, prefill_chunk=8,
            kv_pages=16, page_size=8, max_queue=2)
        rejections = 0
        try:
            # These fill the (denied) admission queue...
            queued = [eng.submit([1, 2, 3], 4) for _ in range(2)]
            # ...so overflow submits during the denial window must be
            # rejected with the 429 class, not crash the engine.
            deadline = time.time() + 0.8
            while time.time() < deadline:
                try:
                    queued.append(eng.submit([4, 5], 4))
                except batching_engine.QueueFull:
                    rejections += 1
                time.sleep(0.02)
            # Window over: the engine must drain the backlog unaided.
            results = [r.result(timeout=120) for r in queued]
            details['completed'] = len(results)
            details['tokens_ok'] = all(len(r) == 4 for r in results)
        finally:
            eng.stop()
        details['rejections'] = rejections
        details['engine_failed'] = eng.stats()['failed']
        details['kv_pages_used'] = eng.stats()['kv_pages_used']
    serve_events = _since(serve_journal, t0)
    _expect(rejections >= 1,
            f'overflow submits saw QueueFull/429 during the denial '
            f'window (got {rejections})', extra)
    _expect(details['engine_failed'] is False,
            'pool exhaustion never failed the engine', extra)
    _expect(details.get('tokens_ok', False),
            'every queued request completed after the window', extra)
    _expect(details['kv_pages_used'] == 0,
            f'pool fully drained at shutdown '
            f'(got {details["kv_pages_used"]} pages used)', extra)
    injected = [e for e in _since(injector.chaos_journal(), t0)
                if e.get('event') == 'chaos_fault_injected']
    _expect(len(injected) >= 1, 'the deny fault actually fired', extra)
    return _finish('page_pool_exhaustion', seed, t0, serve_events,
                   ['page_pool_balance'], extra, details)


@_register(
    'handoff_fallback',
    'KV handoff import denied (deny effect on serve.kv_handoff) -> '
    'the router falls back to LOCAL prefill on the decode replica; '
    'the request completes with the same tokens, nothing is lost or '
    'double-executed (handoff_consistency over the serve journal), '
    'and the next handoff goes through clean')
def handoff_fallback(seed: int) -> ScenarioResult:
    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.serve import load_balancer as lb_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router as router_lib  # pylint: disable=import-outside-toplevel

    # Deny exactly the FIRST import at the decode replica: request 1
    # must complete via local prefill (fallback), request 2's handoff
    # must go through.
    plan = faults_lib.FaultPlan(
        seed=seed, name='handoff_fallback',
        faults=[faults_lib.Fault(site='serve.kv_handoff',
                                 effect='deny', nth=[1])])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))

    def make_server():
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            kv_pages=48, page_size=8, prefill_chunk=16)

    prefill_server = make_server()
    decode_server = make_server()
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1', router=router_lib.Router(threshold=24))
    shutdowns = []
    try:
        p_port, p_stop = model_server_lib.start_background(
            prefill_server)
        shutdowns.append(p_stop)
        d_port, d_stop = model_server_lib.start_background(
            decode_server)
        shutdowns.append(d_stop)
        lb.set_replicas([
            {'url': f'http://127.0.0.1:{p_port}', 'role': 'prefill',
             'page_size': 8},
            {'url': f'http://127.0.0.1:{d_port}', 'role': 'decode',
             'page_size': 8},
        ])
        prompt = list(range(1, 41))   # 40 tokens >= threshold 24
        with _armed(plan):
            lb_port = lb.start()
            responses = []
            for _ in range(2):
                responses.append(requests.post(
                    f'http://127.0.0.1:{lb_port}'
                    f'{http_protocol.GENERATE}',
                    json={'prompt_ids': [prompt],
                          'max_new_tokens': 4},
                    timeout=120))
        details['statuses'] = [r.status_code for r in responses]
        details['tokens'] = [r.json().get('tokens') for r in responses]
        _expect(all(r.status_code == 200 for r in responses),
                f'both requests completed 200 '
                f'(got {details["statuses"]})', extra)
        _expect(details['tokens'][0] == details['tokens'][1],
                'fallback (local prefill) and handoff produced '
                'identical tokens', extra)
        serve_events = _since(serve_journal, t0)
        handoff_ends = [e.get('status') for e in serve_events
                        if e.get('event') == 'kv_handoff_end']
        details['handoff_ends'] = handoff_ends
        _expect(handoff_ends == ['fallback', 'ok'],
                f'first handoff fell back, second succeeded '
                f'(got {handoff_ends})', extra)
        injected = [e for e in _since(injector.chaos_journal(), t0)
                    if e.get('event') == 'chaos_fault_injected']
        _expect(len(injected) == 1,
                f'exactly one deny fault fired (got {len(injected)})',
                extra)
    finally:
        lb.stop()
        for stop in shutdowns:
            stop()
        prefill_server.close()
        decode_server.close()
    return _finish('handoff_fallback', seed, t0, serve_events,
                   ['handoff_consistency'], extra, details)


@_register(
    'error_spike',
    'one rank of a 2-host slice replica dies mid-request (raise on '
    'serve.rank_exec) -> the replica\'s WARN/ERROR log rate spikes '
    'above threshold, the fleet log plane journals '
    'log_error_spike_start, and once the fleet quiets the spike '
    'terminates (log_error_spike_end); journal replay proves every '
    'spike start has its end')
def error_spike(seed: int) -> ScenarioResult:
    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.observability import aggregator as aggregator_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.observability import logs as logs_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel

    # Kill rank 1 on its first coordinated broadcast (rank 0 executes
    # inline = site call 1, rank 1 = call 2) — the admission path then
    # logs the rank death and the failed engine tick, which IS the
    # error burst the log plane must notice.
    plan = faults_lib.FaultPlan(
        seed=seed, name='error_spike',
        faults=[faults_lib.Fault(site='serve.rank_exec',
                                 effect='raise', where={'rank': 1},
                                 nth=[2], max_times=1)])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))
    service = f'chaos-errspike-{seed}'
    # A handful of burst records over the scenario's synthetic clock
    # must clear the threshold; the production default of 1 err/s
    # would need a flood.
    env_keys = {'SKYTPU_LOG_ERROR_SPIKE_THRESHOLD': '0.01'}
    saved_env = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    server = model_server_lib.ModelServer(
        'tiny', max_len=64, max_batch=2, continuous_batching=True,
        prefill_chunk=16, num_hosts=2)
    aggregator = aggregator_lib.FleetAggregator(service)
    tracker = logs_lib.LogSpikeTracker(service, journal=serve_journal)
    stop = None
    try:
        port, stop = model_server_lib.start_background(server)
        targets = [{'url': f'http://127.0.0.1:{port}',
                    'kind': 'replica', 'replica_id': 0,
                    'role': 'mixed'}]
        # Seed both level series so the baseline scrape gives the
        # windowed rate its first sample per level (a series born
        # mid-window has no baseline to rate against).
        with sky_logging.silent():
            logger.warning('chaos error_spike baseline warning')
            logger.error('chaos error_spike baseline error')
        # Scrape timestamps are the scenario's clock (counter values
        # stay real): baseline now, the burst read at now+30, then two
        # flat scrapes past the fast window.
        now = time.time()
        aggregator.scrape_fleet(targets, now)
        baseline = tracker.evaluate(aggregator.store, now)
        _expect(not any(s['spiking'] for s in baseline),
                f'no spike before the fault (got {baseline})', extra)
        with _armed(plan):
            try:
                resp = requests.post(
                    f'http://127.0.0.1:{port}{http_protocol.GENERATE}',
                    json={'prompt_ids': [[1, 2, 3, 4]],
                          'max_new_tokens': 4}, timeout=60)
                details['request_status'] = resp.status_code
            except requests.RequestException:
                details['request_status'] = None  # dying replica
        time.sleep(0.5)  # let the engine's failure logging settle
        aggregator.scrape_fleet(targets, now + 30)
        during = tracker.evaluate(aggregator.store, now + 30)
        details['during'] = during
        _expect(any(s['spiking'] for s in during),
                f'the WARN/ERROR burst starts a spike (got {during})',
                extra)
        aggregator.scrape_fleet(targets, now + 120)
        aggregator.scrape_fleet(targets, now + 125)
        after = tracker.evaluate(aggregator.store, now + 125)
        details['after'] = after
        _expect(not any(s['spiking'] for s in after),
                f'the spike terminates once the fleet quiets '
                f'(got {after})', extra)
    finally:
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
        if stop is not None:
            stop()
        server.close()
    serve_events = _since(serve_journal, t0)
    names = [e.get('event') for e in serve_events]
    _expect('log_error_spike_start' in names,
            'log_error_spike_start was journaled', extra)
    _expect('log_error_spike_end' in names,
            'log_error_spike_end was journaled', extra)
    injected = [e for e in _since(injector.chaos_journal(), t0)
                if e.get('event') == 'chaos_fault_injected']
    _expect(len(injected) == 1,
            f'exactly one rank-death fault fired (got {len(injected)})',
            extra)
    return _finish('error_spike', seed, t0, serve_events,
                   ['log_spike_terminates'], extra, details)


def _run_replica_rank_death(name: str, seed: int,
                            rebuild: bool) -> ScenarioResult:
    """Shared body of replica_rank_death (fast: kill -> LB re-route ->
    retire) and replica_rank_death_rebuild (adds the slow full-rebuild
    roundtrip: a fresh slice replica takes the dead one's place and
    serves)."""
    import requests  # pylint: disable=import-outside-toplevel

    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import load_balancer as lb_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import replica_managers  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router as router_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import serve_state  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import service_spec  # pylint: disable=import-outside-toplevel

    # Kill rank 1 of the slice replica on the FIRST coordinated
    # broadcast after arming: per broadcast, rank 0 executes inline
    # (site call 1) then rank 1 (call 2) — nth=2 is deterministic.
    plan = faults_lib.FaultPlan(
        seed=seed, name=name,
        faults=[faults_lib.Fault(site='serve.rank_exec',
                                 effect='raise', where={'rank': 1},
                                 nth=[2], max_times=1)])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))

    def make_slice():
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            prefill_chunk=16, num_hosts=2)

    slice_server = make_slice()
    solo_server = model_server_lib.ModelServer(
        'tiny', max_len=64, max_batch=2, continuous_batching=True,
        prefill_chunk=16)
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1', router=router_lib.Router(threshold=10_000))
    shutdowns = []
    serve_events: List[Dict[str, Any]] = []
    try:
        s_port, s_stop = model_server_lib.start_background(slice_server)
        shutdowns.append(s_stop)
        b_port, b_stop = model_server_lib.start_background(solo_server)
        shutdowns.append(b_stop)
        slice_url = f'http://127.0.0.1:{s_port}'
        solo_url = f'http://127.0.0.1:{b_port}'
        lb.set_replicas([{'url': slice_url, 'role': 'mixed'},
                         {'url': solo_url, 'role': 'mixed'}])
        lb_port = lb.start()
        base = f'http://127.0.0.1:{lb_port}'

        def gen(prompt, timeout=120):
            return requests.post(
                f'{base}{http_protocol.GENERATE}',
                json={'prompt_ids': [prompt], 'max_new_tokens': 4},
                timeout=timeout)

        # Phase 1 (no faults armed): pin a session onto the SLICE
        # replica via prefix affinity, so the kill provably lands in
        # the path of live traffic.  Idle least-loaded ranking is
        # deterministic by url, so if the organic pin landed on the
        # solo replica, pin the session key explicitly (the router's
        # documented affinity API) and verify the next request really
        # is an affinity HIT on the slice replica.
        probe_prompts = [[p, p + 1, p + 2, p + 3, 9, 9]
                         for p in (10, 20, 30, 40)]
        slice_prompt = None
        warm_statuses = []
        for prompt in probe_prompts:
            warm_statuses.append(gen(prompt).status_code)
            key = router_lib.prompt_key(prompt_ids=prompt)
            if lb.router.affinity_target(key) == slice_url:
                slice_prompt = prompt
                break
        details['warm_statuses'] = warm_statuses
        _expect(all(s == 200 for s in warm_statuses),
                f'warmup requests all 200 (got {warm_statuses})', extra)
        if slice_prompt is None:
            slice_prompt = probe_prompts[0]
            lb.router.record_affinity(
                router_lib.prompt_key(prompt_ids=slice_prompt),
                slice_url)
        pinned = lb.router.route(
            router_lib.prompt_key(prompt_ids=slice_prompt),
            len(slice_prompt))
        _expect(pinned.url == slice_url and pinned.affinity == 'hit',
                f'the session is pinned to the slice replica '
                f'(got {pinned.affinity}/{pinned.url})', extra)

        # Phase 2 (fault armed): the next coordinated broadcast kills
        # rank 1 mid-admission.  Every request must still come back
        # 200 — the LB's same-role 5xx retry re-routes onto the
        # surviving replica while the slice is down.
        with _armed(plan):
            statuses = [gen(slice_prompt).status_code
                        for _ in range(4)]
            details['statuses_during_death'] = statuses
            _expect(all(s == 200 for s in statuses),
                    f'zero lost requests across the rank death '
                    f'(got {statuses})', extra)
            health = requests.get(slice_url + '/', timeout=10)
            details['slice_health_status'] = health.status_code
            payload = health.json()
            details['slice'] = payload.get('slice')
            _expect(health.status_code == 503,
                    f'degraded slice fails its readiness probe '
                    f'(got {health.status_code})', extra)
            _expect(bool((payload.get('slice') or {}).get('degraded')),
                    'health payload carries slice.degraded', extra)
            _expect((payload.get('slice') or {}).get(
                'dead_ranks') == [1], 'rank 1 is the dead rank', extra)

            # Controller-side consequence: the probe retires a
            # degraded slice as a UNIT (NOT_READY -> torn down,
            # FAILED_PROBING) instead of waiting out initial_delay.
            service = f'chaos-rankdeath-{seed}'
            spec = service_spec.SkyServiceSpec(
                initial_delay_seconds=120, readiness_timeout_seconds=5)
            task = sky.Task(name='chaos-rankdeath', run='sleep 1')
            task.set_resources(sky.Resources(cloud='local'))
            serve_state.add_service(service, spec_json={},
                                    task_yaml_path='')
            manager = replica_managers.ReplicaManager(service, spec,
                                                      task)
            replica_id = serve_state.allocate_replica(
                service, service, num_hosts=2)
            serve_state.set_replica_status(
                service, replica_id, serve_state.ReplicaStatus.READY,
                url=slice_url)
            manager._probe_one(  # pylint: disable=protected-access
                serve_state.get_replicas(service)[0])
            retired = serve_state.get_replicas(service)[0]['status']
            details['retired_status'] = retired
            _expect(retired == 'FAILED_PROBING',
                    f'degraded slice retired as a unit '
                    f'(got {retired})', extra)

            # The LB drops the dead replica (as the controller sync
            # would after the retire) and the pinned session re-routes.
            lb.set_replicas([{'url': solo_url, 'role': 'mixed'}])
            after = gen(slice_prompt).status_code
            details['status_after_retire'] = after
            _expect(after == 200,
                    'pinned session re-routed to the survivor', extra)

            if rebuild:
                # Full rebuild roundtrip: a FRESH slice replica (the
                # controller's replacement launch) joins the fleet and
                # serves the same session again.
                shutdowns.append(None)  # placeholder replaced below
                rebuilt = make_slice()
                r_port, r_stop = model_server_lib.start_background(
                    rebuilt)
                shutdowns[-1] = r_stop
                rebuilt_url = f'http://127.0.0.1:{r_port}'
                # Its probe goes READY (fresh gang, no dead ranks)...
                new_id = serve_state.allocate_replica(
                    service, service, num_hosts=2)
                serve_state.set_replica_status(
                    service, new_id,
                    serve_state.ReplicaStatus.STARTING,
                    url=rebuilt_url)
                manager._probe_one(  # pylint: disable=protected-access
                    [r for r in serve_state.get_replicas(service)
                     if r['replica_id'] == new_id][0])
                rebuilt_status = [
                    r for r in serve_state.get_replicas(service)
                    if r['replica_id'] == new_id][0]['status']
                details['rebuilt_status'] = rebuilt_status
                _expect(rebuilt_status == 'READY',
                        f'rebuilt slice probes READY '
                        f'(got {rebuilt_status})', extra)
                # ...and serves through the LB.
                lb.set_replicas([
                    {'url': rebuilt_url, 'role': 'mixed'},
                    {'url': solo_url, 'role': 'mixed'}])
                rebuilt_statuses = [gen(slice_prompt).status_code
                                    for _ in range(3)]
                details['rebuilt_statuses'] = rebuilt_statuses
                _expect(all(s == 200 for s in rebuilt_statuses),
                        f'rebuilt fleet serves (got '
                        f'{rebuilt_statuses})', extra)
                health = requests.get(rebuilt_url + '/', timeout=10)
                _expect(health.status_code == 200,
                        'rebuilt slice is healthy', extra)
                rebuilt.close()
            serve_events = _since(serve_journal, t0)
    finally:
        lb.stop()
        for stop in shutdowns:
            if stop is not None:
                stop()
        slice_server.close()
        solo_server.close()
    injected = [e for e in _since(injector.chaos_journal(), t0)
                if e.get('event') == 'chaos_fault_injected']
    _expect(len(injected) == 1,
            f'exactly one rank-death fault fired (got {len(injected)})',
            extra)
    return _finish(name, seed, t0, serve_events,
                   ['handoff_consistency'], extra, details)


@_register(
    'replica_rank_death',
    'one rank of a 2-host slice replica dies mid-service (raise on '
    'serve.rank_exec) -> the replica fails AS A UNIT (503 + '
    'slice.degraded), the LB re-routes every request to the surviving '
    'replica with zero lost requests (journal-verified), and the '
    'controller probe retires the slice for replacement')
def replica_rank_death(seed: int) -> ScenarioResult:
    return _run_replica_rank_death('replica_rank_death', seed,
                                   rebuild=False)


@_register(
    'replica_rank_death_rebuild',
    'replica_rank_death plus the full rebuild roundtrip: a fresh slice '
    'replica takes the dead one\'s place, probes READY, and serves the '
    'same pinned session through the LB')
def replica_rank_death_rebuild(seed: int) -> ScenarioResult:
    return _run_replica_rank_death('replica_rank_death_rebuild', seed,
                                   rebuild=True)


@_register(
    'drain_under_load',
    'scale-down and a rolling replacement mid-traffic -> every client '
    'request completes 2xx (the LB retire nudge + same-role retry '
    'absorb the retirement), journal replay proves no request was '
    'routed to a replica after its retire event, none was lost or '
    'double-executed, and the retiring replica handed its hot prefix '
    'pages to the surviving sibling')
def drain_under_load(seed: int) -> ScenarioResult:
    import random  # pylint: disable=import-outside-toplevel
    import threading  # pylint: disable=import-outside-toplevel

    import requests  # pylint: disable=import-outside-toplevel

    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import load_balancer as lb_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import replica_managers  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router as router_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import serve_state  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import service_spec  # pylint: disable=import-outside-toplevel

    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))
    service = f'chaos-drain-{seed}'

    def make_server():
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            kv_pages=48, page_size=8, prefill_chunk=16)

    servers = [make_server(), make_server()]
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1', router=router_lib.Router(threshold=10_000))
    shutdowns: List[Any] = []
    statuses: List[int] = []
    statuses_lock = threading.Lock()
    env_keys = {'SKYTPU_SERVE_HANDOFF_EVENTS': '1',
                'SKYTPU_SERVE_DRAIN_TIMEOUT_S': '30'}
    saved_env = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    try:
        urls = []
        for server in servers:
            port, stop = model_server_lib.start_background(server)
            shutdowns.append(stop)
            urls.append(f'http://127.0.0.1:{port}')
        lb.set_replicas([{'url': u, 'role': 'mixed'} for u in urls])
        lb_port = lb.start()

        # The replica fleet as the controller would see it: two READY
        # rows pointing at the live servers; the LB port is registered
        # so begin_drain's retire nudge finds it.
        spec = service_spec.SkyServiceSpec(
            initial_delay_seconds=120, readiness_timeout_seconds=5)
        task = sky.Task(name='chaos-drain', run='sleep 1')
        task.set_resources(sky.Resources(cloud='local'))
        serve_state.add_service(service, spec_json={},
                                task_yaml_path='')
        serve_state.set_service_ports(service, 0, lb_port)
        manager = replica_managers.ReplicaManager(service, spec, task)
        rids = []
        for url in urls:
            rid = serve_state.allocate_replica(service, service)
            serve_state.set_replica_status(
                service, rid, serve_state.ReplicaStatus.READY, url=url)
            rids.append(rid)

        # Live Poisson traffic against the LB while the fleet churns.
        stop_traffic = threading.Event()

        def client(worker: int) -> None:
            worker_rng = random.Random(f'{seed}:{worker}')
            n = 0
            while not stop_traffic.is_set() and n < 40:
                # Long enough that the prefilled region [0, n-1) spans
                # full 8-token pages — the drain-time prefix handoff
                # needs cached pages to ship.
                prompt = ([worker * 50 + (n % 7) + 1] +
                          [3, 5, 7, 9, 11, 13, 15, 17] * 2 + [19, 21])
                try:
                    resp = requests.post(
                        f'http://127.0.0.1:{lb_port}'
                    f'{http_protocol.GENERATE}',
                        json={'prompt_ids': [prompt],
                              'max_new_tokens': 6}, timeout=60)
                    code = resp.status_code
                except requests.RequestException:
                    code = -1
                with statuses_lock:
                    statuses.append(code)
                n += 1
                time.sleep(worker_rng.expovariate(1 / 0.05))

        threads = [threading.Thread(target=client, args=(w,),
                                    daemon=True) for w in range(3)]
        for t in threads:
            t.start()

        def wait_responses(count: int, timeout: float = 30.0) -> None:
            deadline = time.time() + timeout
            while time.time() < deadline:
                with statuses_lock:
                    if len(statuses) >= count:
                        return
                time.sleep(0.05)

        def drain_and_wait(rid: int, reason: str,
                           timeout: float = 30.0) -> str:
            manager.scale_down(rid, drain=True, reason=reason)
            deadline = time.time() + timeout
            while time.time() < deadline:
                row = next(r for r in serve_state.get_replicas(service)
                           if r['replica_id'] == rid)
                if serve_state.ReplicaStatus(
                        row['status']).is_terminal():
                    return row['status']
                manager.sync_draining()
                time.sleep(0.1)
            return 'DRAIN_TIMEOUT'

        # Phase 1: scale-down mid-traffic — replica 1 drains while
        # replicas keep answering.
        wait_responses(6)
        details['scale_down_final'] = drain_and_wait(rids[0],
                                                     'scale_down')
        # Phase 2: rolling replacement — a fresh replica joins (the
        # new version coming READY), then the remaining old replica
        # drains, still under traffic.
        replacement = make_server()
        r_port, r_stop = model_server_lib.start_background(replacement)
        shutdowns.append(r_stop)
        r_url = f'http://127.0.0.1:{r_port}'
        new_rid = serve_state.allocate_replica(service, service)
        serve_state.set_replica_status(
            service, new_rid, serve_state.ReplicaStatus.READY,
            url=r_url)
        lb.set_replicas([{'url': urls[1], 'role': 'mixed'},
                         {'url': r_url, 'role': 'mixed'}])
        wait_responses(14)
        details['rolling_final'] = drain_and_wait(rids[1],
                                                  'rolling_update')
        stop_traffic.set()
        for t in threads:
            t.join(timeout=60)
        servers.append(replacement)
    finally:
        lb.stop()
        for stop in shutdowns:
            stop()
        for server in servers:
            server.close()
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value

    details['requests'] = len(statuses)
    details['statuses'] = sorted(set(statuses))
    _expect(len(statuses) >= 20,
            f'traffic actually ran ({len(statuses)} requests)', extra)
    _expect(all(s == 200 for s in statuses),
            f'ZERO non-2xx client responses across both drains '
            f'(got {details["statuses"]})', extra)
    _expect(details.get('scale_down_final') == 'TERMINATED',
            f'scale-down drain reached TERMINATED '
            f'(got {details.get("scale_down_final")})', extra)
    _expect(details.get('rolling_final') == 'TERMINATED',
            f'rolling-update drain reached TERMINATED '
            f'(got {details.get("rolling_final")})', extra)
    serve_events = _since(serve_journal, t0)
    drain_ends = [(e.get('replica_id'), e.get('reason'))
                  for e in serve_events
                  if e.get('event') == 'replica_drain_end']
    details['drain_ends'] = drain_ends
    _expect(len(drain_ends) == 2 and
            all(reason == 'drained' for _, reason in drain_ends),
            f'both drains finished by running dry, not timeout '
            f'(got {drain_ends})', extra)
    retires = [e.get('url') for e in serve_events
               if e.get('event') == 'lb_retire']
    details['lb_retires'] = retires
    _expect(len(retires) == 2,
            f'the LB processed both retire nudges (got {retires})',
            extra)
    handoffs = [e.get('status') for e in serve_events
                if e.get('event') == 'drain_prefix_handoff']
    details['prefix_handoffs'] = handoffs
    _expect(any(s == 'ok' for s in handoffs),
            f'hot prefix pages handed to a sibling (got {handoffs})',
            extra)
    return _finish('drain_under_load', seed, t0, serve_events,
                   ['drain_no_lost_requests'], extra, details)


@_register(
    'workload_flip_morph',
    'adversarial workload flip (all-prefill burst -> all-decode burst) '
    'mid-traffic -> the fleet rebalances by LIVE role morph: the '
    'prefill replica joins the decode pool without restart (scoped '
    'drain + epoch-stamped retire nudge + in-place budget swap), zero '
    'non-2xx, ITL p99 stays bounded, and journal replay proves the '
    'morph committed with no request lost or double-routed')
def workload_flip_morph(seed: int) -> ScenarioResult:
    import random  # pylint: disable=import-outside-toplevel
    import threading  # pylint: disable=import-outside-toplevel

    import requests  # pylint: disable=import-outside-toplevel

    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import load_balancer as lb_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import replica_managers  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router as router_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import serve_state  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import service_spec  # pylint: disable=import-outside-toplevel

    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))
    service = f'chaos-flip-{seed}'

    def make_server(role: str):
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            kv_pages=48, page_size=8, prefill_chunk=16, role=role)

    # A disaggregated pair under a role-aware router: generate traffic
    # lands on the decode pool, so the prefill replica is the fleet's
    # spare capacity once the workload flips.
    servers = [make_server('prefill'), make_server('decode')]
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1', router=router_lib.Router(threshold=10_000))
    shutdowns: List[Any] = []
    statuses: List[int] = []
    statuses_lock = threading.Lock()
    env_keys = {'SKYTPU_SERVE_HANDOFF_EVENTS': '1',
                'SKYTPU_SERVE_DRAIN_TIMEOUT_S': '30'}
    saved_env = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    # ITL histogram snapshot: the bound below is computed on the DELTA
    # so observations from earlier scenarios in this process don't
    # launder (or poison) this run's tail.
    itl_name = 'skytpu_engine_itl_seconds'
    itl_before = metrics_lib.parse_exposition(metrics_lib.expose())
    flip = threading.Event()
    t_morph = time.time()
    try:
        urls = []
        for server in servers:
            port, stop = model_server_lib.start_background(server)
            shutdowns.append(stop)
            urls.append(f'http://127.0.0.1:{port}')
        lb.set_replicas([{'url': urls[0], 'role': 'prefill'},
                         {'url': urls[1], 'role': 'decode'}])
        lb_port = lb.start()

        spec = service_spec.SkyServiceSpec(
            initial_delay_seconds=120, readiness_timeout_seconds=5)
        task = sky.Task(name='chaos-flip', run='sleep 1')
        task.set_resources(sky.Resources(cloud='local'))
        serve_state.add_service(service, spec_json={},
                                task_yaml_path='')
        serve_state.set_service_ports(service, 0, lb_port)
        manager = replica_managers.ReplicaManager(service, spec, task)
        rids = []
        for url, role in zip(urls, ('prefill', 'decode')):
            rid = serve_state.allocate_replica(service, service,
                                               role=role)
            serve_state.set_replica_status(
                service, rid, serve_state.ReplicaStatus.READY, url=url)
            rids.append(rid)

        stop_traffic = threading.Event()

        def client(worker: int) -> None:
            worker_rng = random.Random(f'{seed}:{worker}')
            n = 0
            while not stop_traffic.is_set() and n < 40:
                if flip.is_set():
                    # Decode-heavy phase: short prompt, long decode.
                    prompt = [worker * 50 + (n % 7) + 1, 3, 5, 7]
                    max_new = 12
                else:
                    # Prefill-heavy phase: page-spanning prompts,
                    # almost no decode.
                    prompt = ([worker * 50 + (n % 7) + 1] +
                              [3, 5, 7, 9, 11, 13, 15, 17] * 2 +
                              [19, 21])
                    max_new = 2
                try:
                    resp = requests.post(
                        f'http://127.0.0.1:{lb_port}'
                        f'{http_protocol.GENERATE}',
                        json={'prompt_ids': [prompt],
                              'max_new_tokens': max_new}, timeout=60)
                    code = resp.status_code
                except requests.RequestException:
                    code = -1
                with statuses_lock:
                    statuses.append(code)
                n += 1
                time.sleep(worker_rng.expovariate(1 / 0.05))

        threads = [threading.Thread(target=client, args=(w,),
                                    daemon=True) for w in range(3)]
        for t in threads:
            t.start()

        def wait_responses(count: int, timeout: float = 30.0) -> None:
            deadline = time.time() + timeout
            while time.time() < deadline:
                with statuses_lock:
                    if len(statuses) >= count:
                        return
                time.sleep(0.05)

        # Phase 1: the all-prefill burst hammers the decode replica
        # alone (prompts stay under the handoff threshold).
        wait_responses(8)
        # Phase 2: the workload flips all-decode mid-traffic; the
        # fleet answers with a LIVE morph — the idle prefill replica
        # joins the decode pool, warm weights and page pool intact.
        flip.set()
        t_morph = time.time()
        details['morphed'] = manager.morph_replica(rids[0], 'decode')
        # The controller's next sync, compressed into a push: the
        # post-morph ready set stamped with a fresh epoch (>= the
        # morph's retire nudge) re-admits the address in its NEW role.
        lb.apply_state({
            'ready': [{'url': urls[0], 'role': 'decode'},
                      {'url': urls[1], 'role': 'decode'}],
            'retired_epoch': replica_managers.next_retire_epoch()})
        wait_responses(24)
        stop_traffic.set()
        for t in threads:
            t.join(timeout=60)

        # The morph must be visible everywhere role is read: the DB
        # row (status tables / scrape targets) and live /health.
        row = next(r for r in serve_state.get_replicas(service)
                   if r['replica_id'] == rids[0])
        details['db_role'] = row.get('role')
        try:
            health = requests.get(urls[0] + '/', timeout=5).json()
            details['health_role'] = health.get('role')
            details['health_draining'] = health.get('draining')
        except (requests.RequestException, ValueError) as e:
            extra.append(f'expectation: post-morph health probe '
                         f'failed ({e})')
    finally:
        lb.stop()
        for stop in shutdowns:
            stop()
        for server in servers:
            server.close()
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value

    itl_after = metrics_lib.parse_exposition(metrics_lib.expose())
    before_buckets = itl_before.get(f'{itl_name}_bucket', {})
    delta = {f'{itl_name}_bucket': {
        labels: value - before_buckets.get(labels, 0.0)
        for labels, value in itl_after.get(f'{itl_name}_bucket',
                                           {}).items()}}
    itl_p99 = metrics_lib.histogram_quantile(delta, itl_name, 0.99)
    details['itl_p99_s'] = itl_p99
    details['requests'] = len(statuses)
    details['statuses'] = sorted(set(statuses))
    _expect(len(statuses) >= 20,
            f'traffic actually ran ({len(statuses)} requests)', extra)
    _expect(all(s == 200 for s in statuses),
            f'ZERO non-2xx client responses across the flip '
            f'(got {details["statuses"]})', extra)
    _expect(details.get('morphed') is True,
            'the live morph committed (morph_replica returned True)',
            extra)
    _expect(details.get('db_role') == 'decode',
            f'serve_state role column tracks the morph '
            f'(got {details.get("db_role")})', extra)
    _expect(details.get('health_role') == 'decode' and
            details.get('health_draining') is False,
            f'replica /health advertises the new role and re-opened '
            f'(role={details.get("health_role")}, '
            f'draining={details.get("health_draining")})', extra)
    _expect(itl_p99 is not None and itl_p99 <= 2.5,
            f'ITL p99 stays bounded through the flip '
            f'(got {itl_p99})', extra)
    serve_events = _since(serve_journal, t0)
    morph_ends = [(e.get('from_role'), e.get('to_role'),
                   e.get('status')) for e in serve_events
                  if e.get('event') == 'role_morph_end']
    details['morph_ends'] = morph_ends
    _expect(('prefill', 'decode', 'ok') in morph_ends,
            f'at least one LIVE morph journaled prefill -> decode '
            f'with a dry drain (got {morph_ends})', extra)
    retires = [e.get('url') for e in serve_events
               if e.get('event') == 'lb_retire']
    details['lb_retires'] = retires
    _expect(len(retires) >= 1,
            f'the morph parked the replica behind a retire nudge '
            f'(got {retires})', extra)
    post_morph_routes = sum(
        1 for e in serve_events
        if e.get('event') == 'lb_route' and urls and
        e.get('url') == urls[0] and e.get('ts', 0.0) >= t_morph)
    details['post_morph_routes'] = post_morph_routes
    _expect(post_morph_routes >= 1,
            f'the morphed replica actually serves decode traffic '
            f'(got {post_morph_routes} routes)', extra)
    return _finish('workload_flip_morph', seed, t0, serve_events,
                   ['drain_no_lost_requests', 'qos_fairness'], extra,
                   details)


@_register(
    'controller_crash_recovery',
    'controller killed and restarted mid-service (plus a chaos-wedged '
    'first tick) -> the new controller re-adopts the live fleet from '
    'serve_state, warm-starts the autoscaler at the live replica '
    'count, and its first real reconcile pass neither launches nor '
    'retires anything')
def controller_crash_recovery(seed: int) -> ScenarioResult:
    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import serve_state  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import service_spec  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve.controller import SkyServeController  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel

    # The new controller's FIRST tick is wedged (deny) — recovery must
    # already have adopted the fleet, and the next tick must still not
    # churn it.
    plan = faults_lib.FaultPlan(
        seed=seed, name='controller_crash_recovery',
        faults=[faults_lib.Fault(site='serve.controller_tick',
                                 effect='deny', nth=[1],
                                 max_times=1)])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    service = f'chaos-ctl-crash-{seed}'
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))

    task = sky.Task(
        name='chaos-ctl',
        run='exec python3 -m http.server $SKYTPU_SERVE_REPLICA_PORT')
    task.set_resources(sky.Resources(cloud='local'))
    task.service = service_spec.SkyServiceSpec(
        min_replicas=1, max_replicas=3, target_qps_per_replica=1.0,
        initial_delay_seconds=60, readiness_timeout_seconds=2)
    yaml_dir = common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'serve'))
    yaml_path = os.path.join(yaml_dir, f'{service}.yaml')
    common_utils.dump_yaml(yaml_path, task.to_yaml_config())
    serve_state.add_service(service, task.service.to_yaml_config(),
                            yaml_path)

    controller = None
    try:
        with _local_cloud_enabled():
            controller = SkyServeController(service)
            # Scale to 2 (as live traffic would have) and drive until
            # both replicas serve.
            controller.autoscalers['mixed'].target_num_replicas = 2
            deadline = time.time() + 120
            while time.time() < deadline:
                controller.reconcile_once()
                if len(controller.replica_manager.ready_urls()) >= 2:
                    break
                time.sleep(0.5)
            ready_before = sorted(
                controller.replica_manager.ready_urls())
            details['ready_before'] = ready_before
            _expect(len(ready_before) == 2,
                    f'fleet of 2 came up (got {ready_before})', extra)

            # CRASH: the controller object is dropped cold — no
            # teardown, no state flush.  The replicas keep serving.
            controller.stop()
            controller = None

            with _armed(plan):
                restarted = SkyServeController(service)
                controller = restarted
                restarted.recover_fleet()
                target = restarted.autoscalers[
                    'mixed'].target_num_replicas
                details['warm_start_target'] = target
                _expect(target == 2,
                        f'autoscaler warm-started at the live count 2, '
                        f'not min_replicas 1 (got {target})', extra)

                def fleet_snapshot():
                    return sorted(
                        (r['replica_id'], r['status'])
                        for r in serve_state.get_replicas(service)
                        if not serve_state.ReplicaStatus(
                            r['status']).is_terminal())

                before = fleet_snapshot()
                restarted.reconcile_once()   # wedged (deny) tick
                restarted.reconcile_once()   # first REAL pass
                after = fleet_snapshot()
                details['fleet_before'] = before
                details['fleet_after'] = after
                _expect(before == after,
                        f'no replica churn in the first post-restart '
                        f'reconcile (before {before}, after {after})',
                        extra)
                _expect(all(s == 'READY' for _, s in after),
                        f'every adopted replica stayed READY '
                        f'(got {after})', extra)
    finally:
        if controller is not None:
            controller.stop()
            controller.replica_manager.terminate_all()

    serve_events = _since(serve_journal, t0)
    recovered = [e for e in serve_events
                 if e.get('event') == 'controller_recovered']
    details['recovered_events'] = [
        (e.get('adopted'), e.get('draining_resumed'))
        for e in recovered]
    _expect(len(recovered) == 1,
            f'exactly one controller_recovered journal event '
            f'(got {len(recovered)})', extra)
    if recovered:
        _expect(len(recovered[0].get('adopted') or []) == 2,
                f'both live replicas were re-adopted '
                f'(got {recovered[0].get("adopted")})', extra)
    injected = [e for e in _since(injector.chaos_journal(), t0)
                if e.get('event') == 'chaos_fault_injected']
    _expect(len(injected) == 1,
            f'exactly one wedged-tick fault fired '
            f'(got {len(injected)})', extra)
    return _finish('controller_crash_recovery', seed, t0, serve_events,
                   [], extra, details)


@_register(
    'serve_replica_flap',
    'readiness probes fail transiently -> the replica flaps READY -> '
    'NOT_READY and returns to READY once probes pass again')
def serve_replica_flap(seed: int) -> ScenarioResult:
    import http.server  # pylint: disable=import-outside-toplevel
    import threading  # pylint: disable=import-outside-toplevel

    import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import replica_managers  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import serve_state  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import service_spec  # pylint: disable=import-outside-toplevel

    plan = faults_lib.FaultPlan(seed=seed, name='serve_replica_flap',
                                faults=[faults_lib.Fault(
                                    site='serve.replica_probe',
                                    effect='raise',
                                    error='RequestException',
                                    nth=[1, 2])])

    class _Health(http.server.BaseHTTPRequestHandler):

        def do_GET(self):  # noqa: N802  (stdlib naming)
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b'{"status": "ok"}')

        def log_message(self, *args):
            del args

    server = http.server.ThreadingHTTPServer(('127.0.0.1', 0), _Health)
    threading.Thread(target=server.serve_forever, daemon=True).start()

    service = f'chaos-flap-{seed}'
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {'service': service, 'transitions': []}
    try:
        spec = service_spec.SkyServiceSpec(readiness_path='/health',
                                           initial_delay_seconds=120,
                                           readiness_timeout_seconds=2)
        task = sky.Task(name='chaos-flap', run='sleep 1')
        task.set_resources(sky.Resources(cloud='local'))
        serve_state.add_service(service, spec_json={}, task_yaml_path='')
        manager = replica_managers.ReplicaManager(service, spec, task)
        replica_id = serve_state.allocate_replica(service, service)
        url = f'http://127.0.0.1:{server.server_address[1]}'
        serve_state.set_replica_status(
            service, replica_id, serve_state.ReplicaStatus.READY, url=url)
        with _armed(plan):
            for _ in range(4):
                replica = serve_state.get_replicas(service)[0]
                manager._probe_one(replica)  # pylint: disable=protected-access
                status = serve_state.get_replicas(service)[0]['status']
                details['transitions'].append(status)
                if (len(details['transitions']) >= 3 and
                        status == 'READY'):
                    break
    finally:
        server.shutdown()

    transitions = details['transitions']
    _expect('NOT_READY' in transitions,
            f'replica flapped to NOT_READY (transitions: {transitions})',
            extra)
    _expect(transitions and transitions[-1] == 'READY',
            f'replica returned to READY (transitions: {transitions})',
            extra)
    # Router-level consequence of a flap: prefix affinity pinned to the
    # dead replica must re-route to a survivor (and re-pin there), not
    # keep sending a session at a black hole.
    from skypilot_tpu.serve import router as router_lib  # pylint: disable=import-outside-toplevel
    rtr = router_lib.Router(threshold=10_000)
    url_a, url_b = 'http://replica-a', 'http://replica-b'
    rtr.set_endpoints([
        router_lib.ReplicaEndpoint(url_a, role='decode'),
        router_lib.ReplicaEndpoint(url_b, role='decode')])
    key = router_lib.prompt_key(prompt_ids=[1, 2, 3, 4])
    first = rtr.route(key, 4)
    rtr.record_affinity(key, first.url)
    pinned = rtr.route(key, 4)
    _expect(pinned.affinity == 'hit' and pinned.url == first.url,
            f'prefix affinity pinned to {first.url} '
            f'(got {pinned.affinity}/{pinned.url})', extra)
    survivor = url_b if first.url == url_a else url_a
    rtr.set_endpoints([router_lib.ReplicaEndpoint(survivor,
                                                  role='decode')])
    rerouted = rtr.route(key, 4)
    _expect(rerouted.url == survivor and rerouted.affinity == 'miss',
            f'affinity re-routed off the dead replica to {survivor} '
            f'(got {rerouted.affinity}/{rerouted.url})', extra)
    rtr.record_affinity(key, rerouted.url)
    repinned = rtr.route(key, 4)
    _expect(repinned.affinity == 'hit' and repinned.url == survivor,
            'affinity re-pinned to the survivor', extra)
    details['affinity_rerouted'] = rerouted.url == survivor
    chaos_events = _since(injector.chaos_journal(), t0)
    injected = [e for e in chaos_events
                if e.get('event') == 'chaos_fault_injected']
    _expect(len(injected) == 2,
            f'exactly two probe faults injected (got {len(injected)})',
            extra)
    return _finish('serve_replica_flap', seed, t0, [], [], extra,
                   details)


@_register(
    'router_instance_death',
    'one router instance of a two-router tier is killed mid-traffic '
    '-> the hash ring re-homes its prefix keys to the survivor, the '
    'shared brain store keeps every pin, every client request still '
    'completes 2xx, and journal replay proves zero lost requests and '
    'no QoS priority inversion')
def router_instance_death(seed: int) -> ScenarioResult:
    import random  # pylint: disable=import-outside-toplevel
    import threading  # pylint: disable=import-outside-toplevel

    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router as router_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router_tier as router_tier_lib  # pylint: disable=import-outside-toplevel

    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))

    def make_server():
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            kv_pages=48, page_size=8, prefill_chunk=16)

    servers = [make_server(), make_server()]
    tier = router_tier_lib.RouterTier(
        'http://127.0.0.1:1', replicas=2,
        router_kwargs={'threshold': 10_000})
    shutdowns: List[Any] = []
    statuses: List[int] = []
    statuses_lock = threading.Lock()
    env_keys = {'SKYTPU_SERVE_HANDOFF_EVENTS': '1'}
    saved_env = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    try:
        urls = []
        for server in servers:
            port, stop = model_server_lib.start_background(server)
            shutdowns.append(stop)
            urls.append(f'http://127.0.0.1:{port}')
        tier.start()
        tier.set_replicas([{'url': u, 'role': 'mixed'} for u in urls])

        # Live traffic resolved through the front door: every request
        # asks the ring which instance owns its prefix (repeat
        # prefixes -> same router -> same replica-side prefix cache).
        # The gate pauses new sends around the kill so the scenario
        # exercises instance death, not torn TCP streams; the sibling
        # retry below covers the residual race.
        stop_traffic = threading.Event()
        gate = threading.Event()
        gate.set()

        def client(worker: int) -> None:
            worker_rng = random.Random(f'{seed}:{worker}')
            n = 0
            while not stop_traffic.is_set() and n < 30:
                gate.wait(timeout=30)
                prompt = ([worker * 50 + (n % 5) + 1] +
                          [3, 5, 7, 9, 11, 13, 15, 17] * 2 + [19, 21])
                qos_class = 'interactive' if n % 2 == 0 else 'batch'
                headers = {router_lib.QOS_CLASS_HEADER: qos_class}
                code = -1
                for _ in range(2):  # once + one sibling retry
                    base = tier.url_for(prompt_ids=prompt)
                    if base is None:
                        break
                    try:
                        resp = requests.post(
                            f'{base}{http_protocol.GENERATE}',
                            json={'prompt_ids': [prompt],
                                  'max_new_tokens': 6},
                            headers=headers, timeout=60)
                        code = resp.status_code
                        break
                    except requests.RequestException:
                        code = -1
                with statuses_lock:
                    statuses.append(code)
                n += 1
                time.sleep(worker_rng.expovariate(1 / 0.05))

        threads = [threading.Thread(target=client, args=(w,),
                                    daemon=True) for w in range(3)]
        for t in threads:
            t.start()

        def wait_responses(count: int, timeout: float = 60.0) -> None:
            deadline = time.time() + timeout
            while time.time() < deadline:
                with statuses_lock:
                    if len(statuses) >= count:
                        return
                time.sleep(0.05)

        def quiesce(timeout: float = 30.0) -> None:
            """Wait until no client request is mid-flight (the
            response count stays flat), so the kill lands on an idle
            listener."""
            deadline = time.time() + timeout
            stable = 0
            with statuses_lock:
                last = len(statuses)
            while time.time() < deadline and stable < 5:
                time.sleep(0.1)
                with statuses_lock:
                    now = len(statuses)
                stable = stable + 1 if now == last else 0
                last = now

        wait_responses(9)
        # Kill the instance that OWNS a hot prefix, so the re-homing
        # is observable: the key must resolve to the survivor after.
        hot_prompt = [1] + [3, 5, 7, 9, 11, 13, 15, 17] * 2 + [19, 21]
        hot_key = router_lib.prompt_key(prompt_ids=hot_prompt)
        victim = tier.owner(hot_key)
        gate.clear()
        quiesce()
        with statuses_lock:
            details['requests_before_kill'] = len(statuses)
        tier.stop_instance(victim.instance_id, reason='killed')
        survivor = tier.owner(hot_key)
        details['victim'] = victim.instance_id
        details['new_owner'] = survivor.instance_id \
            if survivor else None
        gate.set()
        wait_responses(details['requests_before_kill'] + 12)
        stop_traffic.set()
        gate.set()
        for t in threads:
            t.join(timeout=60)
    finally:
        tier.stop()
        for stop in shutdowns:
            stop()
        for server in servers:
            server.close()
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value

    details['requests'] = len(statuses)
    details['statuses'] = sorted(set(statuses))
    details['requests_after_kill'] = (
        len(statuses) - details.get('requests_before_kill', 0))
    _expect(len(statuses) >= 20,
            f'traffic actually ran ({len(statuses)} requests)', extra)
    _expect(all(s == 200 for s in statuses),
            f'ZERO non-2xx client responses across the kill '
            f'(got {details["statuses"]})', extra)
    _expect(details['requests_after_kill'] >= 6,
            f'traffic kept flowing after the kill '
            f'({details["requests_after_kill"]} requests)', extra)
    _expect(details.get('new_owner') is not None and
            details['new_owner'] != details.get('victim'),
            f'the hot prefix key re-homed to the survivor '
            f'(victim={details.get("victim")}, '
            f'owner={details.get("new_owner")})', extra)
    serve_events = _since(serve_journal, t0)
    starts = [e.get('instance') for e in serve_events
              if e.get('event') == 'router_instance_start']
    ends = [(e.get('instance'), e.get('reason'))
            for e in serve_events
            if e.get('event') == 'router_instance_end']
    details['instance_starts'] = starts
    details['instance_ends'] = ends
    _expect(len(starts) == 2,
            f'both router instances journaled start (got {starts})',
            extra)
    _expect((details.get('victim'), 'killed') in ends,
            f'the victim journaled router_instance_end/killed '
            f'(got {ends})', extra)
    qos_classes = sorted({e.get('qos_class') for e in serve_events
                          if e.get('event') == 'qos_request_start'})
    details['qos_classes'] = qos_classes
    _expect(qos_classes == ['batch', 'interactive'],
            f'both QoS classes passed weighted admission '
            f'(got {qos_classes})', extra)
    routers_used = sorted({e.get('router') for e in serve_events
                           if e.get('event') == 'lb_route' and
                           e.get('router')})
    details['routers_used'] = routers_used
    return _finish('router_instance_death', seed, t0, serve_events,
                   ['drain_no_lost_requests', 'qos_fairness'], extra,
                   details)


@_register(
    'region_loss_failover',
    'every replica of the router-local region dies abruptly '
    'mid-traffic -> region-aware dispatch fails over cross-region '
    '(the LB same-role retry absorbs requests caught mid-death), '
    'every client response stays 2xx, and journal replay proves zero '
    'lost requests')
def region_loss_failover(seed: int) -> ScenarioResult:
    import random  # pylint: disable=import-outside-toplevel
    import threading  # pylint: disable=import-outside-toplevel

    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router_tier as router_tier_lib  # pylint: disable=import-outside-toplevel

    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))
    local_region, remote_region = 'us-central1', 'europe-west4'

    def make_server():
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            kv_pages=48, page_size=8, prefill_chunk=16)

    # One replica per region; the router tier lives in us-central1 and
    # prefers it until the region is gone.
    servers = [make_server(), make_server()]
    tier = router_tier_lib.RouterTier(
        'http://127.0.0.1:1', replicas=2, region=local_region,
        router_kwargs={'threshold': 10_000})
    shutdowns: List[Any] = []
    statuses: List[int] = []
    statuses_lock = threading.Lock()
    env_keys = {'SKYTPU_SERVE_HANDOFF_EVENTS': '1'}
    saved_env = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    try:
        urls = []
        for server in servers:
            port, stop = model_server_lib.start_background(server)
            shutdowns.append(stop)
            urls.append(f'http://127.0.0.1:{port}')
        tier.start()
        tier.set_replicas([
            {'url': urls[0], 'role': 'mixed', 'region': local_region},
            {'url': urls[1], 'role': 'mixed',
             'region': remote_region}])

        stop_traffic = threading.Event()

        def client(worker: int) -> None:
            worker_rng = random.Random(f'{seed}:{worker}')
            n = 0
            while not stop_traffic.is_set() and n < 30:
                prompt = ([worker * 50 + (n % 5) + 1] +
                          [3, 5, 7, 9, 11, 13, 15, 17] * 2 + [19, 21])
                code = -1
                for _ in range(2):  # once + one sibling retry
                    base = tier.url_for(prompt_ids=prompt)
                    if base is None:
                        break
                    try:
                        resp = requests.post(
                            f'{base}{http_protocol.GENERATE}',
                            json={'prompt_ids': [prompt],
                                  'max_new_tokens': 6}, timeout=60)
                        code = resp.status_code
                        break
                    except requests.RequestException:
                        code = -1
                with statuses_lock:
                    statuses.append(code)
                n += 1
                time.sleep(worker_rng.expovariate(1 / 0.05))

        threads = [threading.Thread(target=client, args=(w,),
                                    daemon=True) for w in range(2)]
        for t in threads:
            t.start()

        def wait_responses(count: int, timeout: float = 60.0) -> None:
            deadline = time.time() + timeout
            while time.time() < deadline:
                with statuses_lock:
                    if len(statuses) >= count:
                        return
                time.sleep(0.05)

        wait_responses(8)
        with statuses_lock:
            details['requests_before_loss'] = len(statuses)
        # Full region loss, ABRUPT: the local replica's server dies
        # first (requests caught mid-death ride the LB's same-role
        # retry to the surviving region), THEN the control plane
        # notices and pushes the shrunken ready set.
        shutdowns[0]()
        servers[0].close()
        time.sleep(0.2)
        tier.apply_state({'ready': [
            {'url': urls[1], 'role': 'mixed',
             'region': remote_region}]})
        wait_responses(details['requests_before_loss'] + 10)
        stop_traffic.set()
        for t in threads:
            t.join(timeout=120)
    finally:
        tier.stop()
        for stop in shutdowns[1:]:
            stop()
        for server in servers[1:]:
            server.close()
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value

    details['requests'] = len(statuses)
    details['statuses'] = sorted(set(statuses))
    details['requests_after_loss'] = (
        len(statuses) - details.get('requests_before_loss', 0))
    _expect(len(statuses) >= 16,
            f'traffic actually ran ({len(statuses)} requests)', extra)
    _expect(all(s == 200 for s in statuses),
            f'ZERO non-2xx client responses across the region loss '
            f'(got {details["statuses"]})', extra)
    _expect(details['requests_after_loss'] >= 6,
            f'traffic kept flowing after the region loss '
            f'({details["requests_after_loss"]} requests)', extra)
    serve_events = _since(serve_journal, t0)
    routes = [e for e in serve_events if e.get('event') == 'lb_route']
    local_routes = [e for e in routes
                    if e.get('region') == local_region]
    cross = [e for e in routes if e.get('cross_region')]
    details['local_routes'] = len(local_routes)
    details['cross_region_routes'] = len(cross)
    _expect(len(local_routes) >= 1,
            'region-aware dispatch preferred the local region before '
            'the loss', extra)
    _expect(len(cross) >= 1 and
            all(e.get('region') == remote_region for e in cross),
            f'dispatch failed over cross-region to {remote_region} '
            f'({len(cross)} cross-region routes)', extra)
    return _finish('region_loss_failover', seed, t0, serve_events,
                   ['drain_no_lost_requests'], extra, details)


@_register(
    'batch_resume',
    'batch-infer driver killed mid-commit (raise between the output '
    'append and the ledger append) AND one replica killed mid-shard, '
    'plus a live /weights_swap landing mid-run -> a fresh driver '
    'resumes off the shard ledger and completes with exactly-once '
    'outputs; the KV pool and an in-flight interactive request '
    'survive the swap')
def batch_resume(seed: int) -> ScenarioResult:
    import json  # pylint: disable=import-outside-toplevel
    import tempfile  # pylint: disable=import-outside-toplevel
    import threading  # pylint: disable=import-outside-toplevel

    import flax.linen as nn  # pylint: disable=import-outside-toplevel
    import jax  # pylint: disable=import-outside-toplevel
    import jax.numpy as jnp  # pylint: disable=import-outside-toplevel
    import orbax.checkpoint as ocp  # pylint: disable=import-outside-toplevel
    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.batch import manifest as manifest_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.batch import runner as runner_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.data import checkpoints  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.models import configs  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.models.transformer import Transformer  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import load_balancer as lb_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import model_server as model_server_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import router as router_lib  # pylint: disable=import-outside-toplevel

    # Kill the driver's 3rd row commit BETWEEN its two appends: the
    # output row lands, the ledger record does not — the exactly-once
    # seam.  The raise unwinds the whole first driver incarnation.
    plan = faults_lib.FaultPlan(
        seed=seed, name='batch_resume',
        faults=[faults_lib.Fault(site='batch.shard_write',
                                 effect='raise', nth=[3])])
    t0 = time.time()
    extra: List[str] = []
    details: Dict[str, Any] = {}
    serve_journal = events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))

    tmp = tempfile.mkdtemp(prefix='skytpu-chaos-batch-')
    input_path = os.path.join(tmp, 'input.jsonl')
    with open(input_path, 'w', encoding='utf-8') as f:
        for i in range(10):
            f.write(json.dumps(
                {'prompt_ids': [i + 1, 3, 5, 7, 9]}) + '\n')
    run_dir = os.path.join(tmp, 'run')
    manifest = manifest_lib.build_manifest(input_path, run_dir,
                                           num_shards=3)

    # The swap target: a REAL orbax checkpoint of differently-seeded
    # tiny weights, saved in the training layout (params subtree) the
    # serve-side partial restore reads.
    cfg = configs.get_config('tiny')
    swap_params = nn.meta.unbox(Transformer(cfg).init(
        jax.random.PRNGKey(seed + 1),
        jnp.zeros((1, 8), jnp.int32))['params'])
    ckpt_dir = os.path.join(tmp, 'ckpt')
    mgr = checkpoints.checkpoint_manager(ckpt_dir)
    mgr.save(1, args=ocp.args.StandardSave({'params': swap_params}))
    mgr.wait_until_finished()

    def make_server():
        return model_server_lib.ModelServer(
            'tiny', max_len=64, max_batch=2, continuous_batching=True,
            kv_pages=48, page_size=8, prefill_chunk=16)

    servers = [make_server(), make_server()]
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:1',
        router=router_lib.Router(threshold=10_000))
    shutdowns: List[Any] = []
    summary: Dict[str, Any] = {}
    try:
        urls = []
        for server in servers:
            port, stop = model_server_lib.start_background(server)
            shutdowns.append(stop)
            urls.append(f'http://127.0.0.1:{port}')
        lb.set_replicas([{'url': u, 'role': 'mixed'} for u in urls])
        lb_port = lb.start()
        endpoint = f'http://127.0.0.1:{lb_port}'
        with _armed(plan):
            # Incarnation 1: dies mid-commit on the chaos raise.
            job = runner_lib.BatchInferJob(
                run_dir, endpoint, max_new_tokens=4, inflight=2)
            died = False
            try:
                job.run()
            except faults_lib.ChaosError:
                died = True
            job.ledger.close()
            _expect(died, 'the chaos raise killed the first driver '
                    'incarnation mid-commit', extra)
            done_rows, _ = job.ledger.replay()
            details['rows_before_resume'] = len(done_rows)
            _expect(0 < len(done_rows) < manifest.total_rows,
                    f'the first incarnation committed some but not '
                    f'all rows (got {len(done_rows)})', extra)

            # Replica death mid-shard: the second replica dies
            # abruptly; the LB's same-role failover carries the
            # resume's requests to the survivor.
            shutdowns[1]()
            servers[1].close()

            # Live weight swap mid-run, with an interactive request in
            # flight: the swap must drop neither the KV pool nor the
            # request.
            interactive: Dict[str, Any] = {}

            def interactive_request() -> None:
                try:
                    r = requests.post(
                        f'{urls[0]}{http_protocol.GENERATE}',
                        json={'prompt_ids': [[2, 4, 6, 8, 10]],
                              'max_new_tokens': 16}, timeout=60)
                    interactive['status'] = r.status_code
                    interactive['tokens'] = len(
                        (r.json().get('tokens') or [[]])[0])
                except requests.RequestException:
                    interactive['status'] = -1

            th = threading.Thread(target=interactive_request,
                                  daemon=True)
            th.start()
            swap = requests.post(
                f'{urls[0]}{http_protocol.WEIGHTS_SWAP}',
                json={'checkpoint_dir': ckpt_dir}, timeout=120)
            th.join(timeout=60)
            details['swap_status'] = swap.status_code
            details['interactive'] = dict(interactive)
            _expect(swap.status_code == 200,
                    f'live weight swap succeeded (HTTP '
                    f'{swap.status_code}: {swap.text[:200]})', extra)
            swap_version = (swap.json().get('weight_version')
                            if swap.status_code == 200 else None)
            _expect(swap_version == 1,
                    f'the swap bumped the weight epoch to 1 '
                    f'(got {swap_version})', extra)
            _expect(interactive.get('status') == 200 and
                    interactive.get('tokens') == 16,
                    f'the in-flight interactive request survived the '
                    f'swap (got {interactive})', extra)
            health = requests.get(f'{urls[0]}/', timeout=10).json()
            details['weight_version'] = health.get('weight_version')
            _expect(health.get('weight_version') == 1,
                    f'the health payload reports the bumped weight '
                    f'version (got {health.get("weight_version")})',
                    extra)

            # Incarnation 2: resume off the ledger — must complete
            # with exactly-once outputs despite the dead replica.
            job2 = runner_lib.BatchInferJob(
                run_dir, endpoint, max_new_tokens=4, inflight=2)
            summary = job2.run()
            job2.ledger.close()
            details['summary'] = summary
            stats = servers[0]._engine.stats()  # pylint: disable=protected-access
            details['engine_failed'] = stats['failed']
            details['kv_pages_used'] = stats['kv_pages_used']
            details['weight_epoch'] = stats.get('weight_epoch')
    finally:
        lb.stop()
        shutdowns[0]()
        servers[0].close()

    output = manifest_lib.ShardLedger(run_dir).output_rows(manifest)
    keys = {(r.get('shard'), r.get('row_idx')) for r in output}
    details['output_rows'] = len(output)
    _expect(len(output) == manifest.total_rows and
            len(keys) == manifest.total_rows,
            f'deduped outputs exactly cover the manifest '
            f'({len(output)} rows, {len(keys)} unique)', extra)
    _expect(all(len(r.get('tokens') or []) == 4 for r in output),
            'every output row carries its generated tokens', extra)
    details['rows_on_new_weights'] = sum(
        1 for r in output if r.get('weight_version') == 1)
    _expect(details['rows_on_new_weights'] >= 1,
            'resumed rows are stamped with the post-swap weight '
            'version', extra)
    _expect(summary.get('duplicates_dropped', 0) >= 1,
            f'the half-committed row re-ran and deduped on rewrite '
            f'(dropped {summary.get("duplicates_dropped")})', extra)
    _expect(summary.get('resumed') is True,
            'the second incarnation actually resumed off the ledger',
            extra)
    _expect(details.get('engine_failed') is False,
            'the swap never failed the engine', extra)
    _expect(details.get('kv_pages_used') == 0,
            f'KV pool intact and fully drained after the swap '
            f'(got {details.get("kv_pages_used")} pages used)', extra)
    _expect(details.get('weight_epoch') == 1,
            f'engine weight epoch settled at 1 '
            f'(got {details.get("weight_epoch")})', extra)
    injected = [e for e in _since(injector.chaos_journal(), t0)
                if e.get('event') == 'chaos_fault_injected']
    _expect(len(injected) == 1,
            f'exactly one mid-commit raise fired '
            f'(got {len(injected)})', extra)
    serve_events = _since(serve_journal, t0)
    return _finish('batch_resume', seed, t0, serve_events,
                   ['batch_exactly_once'], extra, details)
