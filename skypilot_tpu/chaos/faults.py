"""Fault-plan DSL: what to break, when, and how — deterministically.

A :class:`FaultPlan` is a seed plus an ordered list of :class:`Fault`s.
Each fault names a registered *site* (the vocabulary below — enforced by
an AST lint in tests), a *trigger* (nth call at the site, every-k,
seeded probability, a time window after arming, and/or a ``where`` match
on the call context), and an *effect*:

    raise    raise a typed error (``error`` picks the class)
    preempt  kill the cluster named in the call ctx, then raise — the
             closest local-backend analogue of a TPU slice eviction
    delay    sleep ``delay_s`` then continue
    hang     sleep ``deadline_s`` then raise (a stuck cloud API call)
    deny     return the DENY sentinel; cooperative sites interpret it
             as "the guarded operation reported not-ready/failed"

Plans load from JSON (inline, a path, or ``@path``) — the
``SKYTPU_CHAOS_PLAN`` environment variable uses the same forms, which is
how a plan armed in the client propagates into emulated-host
subprocesses (gang supervisor, skylet).

Determinism: probability draws come from a per-fault
``random.Random(f'{seed}:{fault_index}')``, and per-site call counters
are process-local — the same plan + seed over the same call sequence
yields a byte-identical fault sequence (guarded by a test).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Union

from skypilot_tpu import exceptions

# Environment variable carrying the armed plan (inline JSON, a path to a
# .json file, or '@<path>').
PLAN_ENV_VAR = 'SKYTPU_CHAOS_PLAN'


class ChaosError(exceptions.SkyTpuError):
    """Default error raised by injected faults."""


# Site vocabulary: every `inject(<site>, ...)` call site must use one of
# these names, and every name must have >= 1 call site (AST lint:
# tests/unit/test_chaos_sites_lint.py).  Keep docs/chaos.md's table in
# sync.
SITES: Dict[str, str] = {
    'provision.create':
        'RetryingProvisioner zone attempt, before the cloud create call '
        '(backends/slice_backend.py) — raise ProvisionError here to '
        'drive the failover loop',
    'queued_resource.poll':
        'wait_for_queued_capacity poll (provision/provisioner.py) — '
        'cooperative: effect "deny" makes the poll report not-granted',
    'runner.exec':
        'CommandRunner.run_with_retry attempt (utils/command_runner.py) '
        '— raise TransientRunnerError to exercise the retry loop',
    'gang.rank_exec':
        'gang supervisor per-rank exec (backends/gang_supervisor.py) — '
        'a raise kills that rank and triggers the gang abort',
    'jobs.status_poll':
        'managed-jobs controller job-status poll (jobs/controller.py) — '
        'effect "preempt" downs the task cluster behind the '
        'controller\'s back, the local analogue of a slice eviction',
    'jobs.recover':
        'recovery strategy recover() (jobs/recovery_strategy.py) — '
        'raise ResourcesUnavailableError to fail a recovery attempt',
    'serve.replica_probe':
        'replica readiness probe (serve/replica_managers.py) — raise '
        'RequestException (or ChaosError) to flap a replica',
    'serve.page_pool':
        'KV page-pool allocation (serve/cache_manager.py PagePool.'
        'alloc) — effect "deny" makes the pool report exhaustion (the '
        'engine must degrade to admission backpressure / HTTP 429, '
        'never an engine failure); "delay" slows admissions (running '
        'decodes must keep their bounded ITL)',
    'serve.rank_exec':
        'slice-replica rank command execution (serve/coordinator.py '
        '_execute — the gang protocol of a multi-host serving '
        'replica) — a raise is that host dying mid-command: the '
        'coordinator marks the rank dead, the replica fails AS A '
        'UNIT (/health 503 with slice.degraded), the controller '
        'retires and replaces it, and the LB re-routes to surviving '
        'replicas with zero lost requests',
    'serve.controller_tick':
        'serve controller reconcile pass (serve/controller.py '
        'reconcile_once) — effect "deny" skips the tick (a wedged/'
        'paused control plane: the LB must keep serving its last-'
        'known replica set), "delay" slows it, a raise is a crashing '
        'tick the run loop must survive; the serving data plane must '
        'tolerate all three',
    'serve.kv_handoff':
        'KV page handoff import (serve/batching_engine.py '
        'import_pages, the decode side of prefill/decode '
        'disaggregation) — effect "deny" makes the decode replica '
        'refuse the pages (the router must fall back to local '
        'prefill; the request completes either way); "delay" adds '
        'handoff latency without stalling decode ticks',
    'serve.router_push':
        'brain-store delta replication to a sibling router instance '
        '(serve/brain_store.py ReplicatedBrainStore._fan_out) — effect '
        '"deny" (or a raise) fails the push: the sibling must converge '
        'through its own controller sync, and the epoch-guarded '
        'retired set must keep a dropped retire-delta from ever '
        'resurrecting a replica',
    'serve.role_morph':
        'live role-morph driver (serve/replica_managers.py '
        'morph_replica, the ISSUE 17 dynamic co-location flip) — '
        'effect "deny" aborts the morph before the scoped drain (the '
        'replica must keep serving under its OLD role and budget; no '
        'request may be lost either way), "delay" stretches the '
        'drain-to-commit window (routers must not double-route during '
        'the epoch-stamped flip), a raise is the controller dying '
        'mid-morph: the journaled role_morph lifecycle must still '
        'terminate',
    'batch.shard_write':
        'batch-infer output/ledger write (batch/manifest.py '
        'ShardLedger.commit_row — the exactly-once seam: the output '
        'row is appended BEFORE its ledger record) — a raise between '
        'the two appends is the driver dying mid-commit: resume must '
        're-run the row and the rewrite dedupe must keep exactly one '
        'output copy; "delay" stretches the commit window',
    'skylet.tick':
        'skylet periodic event run (skylet/events.py) — a raise counts '
        'as an event failure and exercises the failure backoff',
    'checkpoint.save':
        'checkpoint write attempt (data/checkpoints.py '
        'AsyncCheckpointManager) — a raise is a bucket-write flake; '
        'the retry-with-backoff loop is the code under test',
}

EFFECTS = ('raise', 'preempt', 'delay', 'hang', 'deny')


def _error_types() -> Dict[str, Any]:
    """Name -> exception class for the `raise` effect.  Built lazily so
    importing faults.py never drags in requests."""
    import requests  # pylint: disable=import-outside-toplevel
    return {
        'ChaosError': ChaosError,
        'ProvisionError': exceptions.ProvisionError,
        'ResourcesUnavailableError': exceptions.ResourcesUnavailableError,
        'TransientRunnerError': exceptions.TransientRunnerError,
        'CommandError': None,  # needs args; built in make_error
        'RequestException': requests.RequestException,
        'TimeoutError': TimeoutError,
        'OSError': OSError,
        'RuntimeError': RuntimeError,
    }


@dataclasses.dataclass
class Fault:
    """One fault: site + trigger + effect."""
    site: str
    effect: str = 'raise'
    # Effect parameters.
    error: str = 'ChaosError'
    message: Optional[str] = None
    delay_s: float = 0.0
    deadline_s: float = 0.0
    # preempt only: evict just these host ranks (a PARTIAL preemption —
    # the survivors stay up, the elastic-recovery trigger).  None/empty
    # keeps the whole-cluster eviction.
    ranks: Optional[Sequence[int]] = None
    # Trigger: at most one of nth/every/probability; all other given
    # conditions AND together.  Call numbers are 1-based per site.
    nth: Optional[Union[int, Sequence[int]]] = None
    every: Optional[int] = None
    probability: Optional[float] = None
    max_times: Optional[int] = None
    after_s: float = 0.0
    until_s: Optional[float] = None
    where: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.site not in SITES:
            raise ValueError(
                f'Unknown chaos site {self.site!r}; registered sites: '
                f'{sorted(SITES)}')
        if self.effect not in EFFECTS:
            raise ValueError(
                f'Unknown chaos effect {self.effect!r}; one of {EFFECTS}')
        selectors = [s for s in (self.nth, self.every, self.probability)
                     if s is not None]
        if len(selectors) > 1:
            raise ValueError(
                'A fault takes at most one of nth/every/probability')
        if self.probability is not None and not 0.0 <= self.probability <= 1.0:
            raise ValueError('probability must be in [0, 1]')
        if isinstance(self.nth, int):
            self.nth = [self.nth]
        elif self.nth is not None:
            self.nth = [int(n) for n in self.nth]
        if self.ranks is not None:
            self.ranks = [int(r) for r in self.ranks]
            if self.effect != 'preempt':
                raise ValueError(
                    "'ranks' (partial preemption) only applies to the "
                    "'preempt' effect")

    def matches_ctx(self, ctx: Dict[str, Any]) -> bool:
        """`where` is satisfied iff every key is present in ctx with an
        equal value (string-compared, so JSON '1' matches int rank 1)."""
        for key, want in self.where.items():
            if key not in ctx or str(ctx[key]) != str(want):
                return False
        return True

    def make_error(self) -> Exception:
        message = self.message or (
            f'chaos: injected {self.error} at {self.site}')
        if self.error == 'CommandError':
            return exceptions.CommandError(returncode=255,
                                           command=f'chaos@{self.site}',
                                           error_msg=message)
        cls = _error_types().get(self.error)
        if cls is None:
            raise ValueError(f'Unknown chaos error type {self.error!r}; '
                             f'one of {sorted(_error_types())}')
        return cls(message)

    def to_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        # Drop defaults for compact plans.
        for key, default in (('error', 'ChaosError'), ('message', None),
                             ('delay_s', 0.0), ('deadline_s', 0.0),
                             ('ranks', None),
                             ('nth', None), ('every', None),
                             ('probability', None), ('max_times', None),
                             ('after_s', 0.0), ('until_s', None),
                             ('where', {})):
            if out.get(key) == default:
                out.pop(key, None)
        return out


@dataclasses.dataclass
class FaultPlan:
    """A seed + ordered faults.  First matching fault at a site wins."""
    seed: int = 0
    faults: List[Fault] = dataclasses.field(default_factory=list)
    name: str = ''

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> 'FaultPlan':
        if not isinstance(data, dict):
            raise ValueError(f'Fault plan must be a JSON object, got '
                             f'{type(data).__name__}')
        unknown = set(data) - {'seed', 'faults', 'name'}
        if unknown:
            raise ValueError(f'Unknown fault-plan keys: {sorted(unknown)}')
        faults = [f if isinstance(f, Fault) else Fault(**f)
                  for f in data.get('faults', [])]
        return cls(seed=int(data.get('seed', 0)), faults=faults,
                   name=str(data.get('name', '')))

    @classmethod
    def from_json(cls, text: str) -> 'FaultPlan':
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_env_value(cls, value: str) -> 'FaultPlan':
        """Parse the SKYTPU_CHAOS_PLAN forms: inline JSON, '@<path>', or
        a bare path ending in .json."""
        value = value.strip()
        if value.startswith('@'):
            path = os.path.expanduser(value[1:])
            with open(path, encoding='utf-8') as f:
                return cls.from_json(f.read())
        if value.endswith('.json') and not value.startswith('{'):
            with open(os.path.expanduser(value), encoding='utf-8') as f:
                return cls.from_json(f.read())
        return cls.from_json(value)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {'seed': self.seed,
                               'faults': [f.to_dict() for f in self.faults]}
        if self.name:
            out['name'] = self.name
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def sites(self) -> List[str]:
        return sorted({f.site for f in self.faults})
