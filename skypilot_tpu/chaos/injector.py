"""Process-global fault injector: `inject(site, **ctx)` hooks.

Instrumented code calls ``inject('<site>', **ctx)`` at each registered
site.  With no plan armed this is a no-op fast path (one module-global
read + one environment lookup); with a plan armed the call may raise a
typed error, sleep, down a cluster, or return the :data:`DENY`
sentinel — per the plan's triggers.

Arming:

- :func:`arm` / :func:`disarm` — programmatic (the scenario runner and
  tests).
- ``SKYTPU_CHAOS_PLAN`` — environment; checked lazily on every inject
  call while nothing is armed programmatically, so subprocesses that
  inherit the client's environment (the gang supervisor on an emulated
  head host, the skylet) arm themselves without code changes.  Parsed
  plans are cached per env value; a malformed value logs one warning
  and behaves as no-plan (chaos must never be the thing that breaks
  production paths).

Every fired fault is journaled as ``chaos_fault_injected{site,effect}``
in the chaos journal (``$SKYTPU_HOME/events/chaos.jsonl`` — shared by
all processes of one home, so supervisor-side injections land next to
client-side ones) and bumps ``skytpu_chaos_faults_total{site,effect}``.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import faults as faults_lib
from skypilot_tpu.observability import events as events_lib

logger = sky_logging.init_logger(__name__)

# Sentinel returned by `inject` when a 'deny' fault fires; cooperative
# sites (queued_resource.poll) treat it as "operation reported failure".
DENY = object()

_SCALAR_TYPES = (str, int, float, bool)


class ArmedPlan:
    """One armed plan: per-site call counters + per-fault RNG/state."""

    def __init__(self, plan: faults_lib.FaultPlan) -> None:
        self.plan = plan
        self.armed_at = time.monotonic()
        self._lock = threading.Lock()
        self._site_calls: Dict[str, int] = {}
        self._fired_counts: Dict[int, int] = {}
        # Per-fault RNG keyed off (seed, fault index): probability draws
        # stay deterministic regardless of how faults interleave across
        # sites and threads.
        self._rngs = [random.Random(f'{plan.seed}:{i}')
                      for i in range(len(plan.faults))]
        self.fault_log: List[Dict[str, Any]] = []

    def site_calls(self, site: str) -> int:
        with self._lock:
            return self._site_calls.get(site, 0)

    def fire(self, site: str, ctx: Dict[str, Any]) -> Optional[object]:
        """Count the call; fire the first matching fault (if any)."""
        if site not in faults_lib.SITES:
            raise ValueError(f'inject() called with unregistered site '
                             f'{site!r}; add it to chaos/faults.py SITES')
        with self._lock:
            call_no = self._site_calls.get(site, 0) + 1
            self._site_calls[site] = call_no
            elapsed = time.monotonic() - self.armed_at
            fault = None
            fault_idx = -1
            for idx, candidate in enumerate(self.plan.faults):
                if candidate.site != site:
                    continue
                if not candidate.matches_ctx(ctx):
                    continue
                if elapsed < candidate.after_s:
                    continue
                if (candidate.until_s is not None and
                        elapsed > candidate.until_s):
                    continue
                if (candidate.max_times is not None and
                        self._fired_counts.get(idx, 0) >=
                        candidate.max_times):
                    continue
                if candidate.nth is not None:
                    if call_no not in candidate.nth:
                        continue
                elif candidate.every is not None:
                    if call_no % candidate.every != 0:
                        continue
                elif candidate.probability is not None:
                    if self._rngs[idx].random() >= candidate.probability:
                        continue
                fault = candidate
                fault_idx = idx
                break
            if fault is None:
                return None
            self._fired_counts[fault_idx] = (
                self._fired_counts.get(fault_idx, 0) + 1)
            record = {
                'n': len(self.fault_log) + 1,
                'site': site,
                'effect': fault.effect,
                'fault_index': fault_idx,
                'call': call_no,
                'ctx': {k: v for k, v in sorted(ctx.items())
                        if isinstance(v, _SCALAR_TYPES)},
            }
            self.fault_log.append(record)
        self._record(record, fault)
        return self._apply(fault, ctx)

    # Journal-record field names ctx keys must not shadow.
    _RESERVED_FIELDS = frozenset(
        {'ts', 'seq', 'event', 'site', 'effect', 'call', 'error'})

    def _record(self, record: Dict[str, Any],
                fault: faults_lib.Fault) -> None:
        chaos_faults_total().labels(site=record['site'],
                                    effect=record['effect']).inc()
        ctx_fields = {
            (k if k not in self._RESERVED_FIELDS else f'ctx_{k}'): v
            for k, v in record['ctx'].items()
        }
        try:
            chaos_journal().append('chaos_fault_injected',
                                   site=record['site'],
                                   effect=record['effect'],
                                   call=record['call'],
                                   error=(fault.error
                                          if fault.effect in ('raise',
                                                              'preempt',
                                                              'hang')
                                          else None),
                                   **ctx_fields)
        except Exception:  # pylint: disable=broad-except
            pass  # the recorder must never mask the fault itself

    def _apply(self, fault: faults_lib.Fault,
               ctx: Dict[str, Any]) -> Optional[object]:
        # Sleeps happen OUTSIDE the lock: a hanging site must not block
        # other threads' injections.
        if fault.effect == 'delay':
            time.sleep(fault.delay_s)
            return None
        if fault.effect == 'hang':
            time.sleep(fault.deadline_s)
            raise fault.make_error()
        if fault.effect == 'deny':
            return DENY
        if fault.effect == 'preempt':
            self._preempt(ctx, fault.ranks)
            raise fault.make_error()
        raise fault.make_error()  # 'raise'

    @staticmethod
    def _preempt(ctx: Dict[str, Any],
                 ranks: Optional[List[int]] = None) -> None:
        """Kill the cluster named in ctx — the local-backend analogue of
        a slice eviction (the controller sees the cluster vanish).  With
        `ranks`, only those hosts are evicted (a PARTIAL preemption: the
        survivors stay up and elastic recovery can shrink onto them)."""
        cluster = ctx.get('cluster')
        if not cluster:
            logger.warning('chaos preempt effect fired without a '
                           '`cluster` in ctx; nothing to kill')
            return
        if ranks:
            from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
            from skypilot_tpu import provision  # pylint: disable=import-outside-toplevel
            try:
                record = global_user_state.get_cluster_from_name(
                    str(cluster))
                provider = record['handle'].provider_name
                evicted = provision.evict_instances(provider,
                                                    str(cluster), ranks)
                logger.warning(f'chaos partial preempt of {cluster}: '
                               f'evicted {evicted}')
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'chaos partial preempt of {cluster} '
                               f'failed: {e}')
            return
        from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
        try:
            core.down(str(cluster))
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'chaos preempt of {cluster} failed: {e}')


# ------------------------------------------------------------- module state

_armed: Optional[ArmedPlan] = None
_arm_lock = threading.Lock()
# Parsed-env cache: (env value, ArmedPlan or None-if-malformed).
_env_cache: Optional[Tuple[str, Optional[ArmedPlan]]] = None


def arm(plan: faults_lib.FaultPlan) -> ArmedPlan:
    """Arm a plan programmatically (overrides the env var)."""
    global _armed
    with _arm_lock:
        _armed = ArmedPlan(plan)
        return _armed


def disarm() -> None:
    """Disarm and drop any cached env-parsed plan."""
    global _armed, _env_cache
    with _arm_lock:
        _armed = None
        _env_cache = None


def current() -> Optional[ArmedPlan]:
    """The armed plan, if any: programmatic first, then env."""
    armed = _armed
    if armed is not None:
        return armed
    value = os.environ.get(faults_lib.PLAN_ENV_VAR)
    if not value:
        return None
    return _arm_from_env(value)


def _arm_from_env(value: str) -> Optional[ArmedPlan]:
    global _env_cache
    with _arm_lock:
        if _env_cache is not None and _env_cache[0] == value:
            return _env_cache[1]
        try:
            armed: Optional[ArmedPlan] = ArmedPlan(
                faults_lib.FaultPlan.from_env_value(value))
        except (ValueError, OSError, TypeError) as e:
            logger.warning(f'Ignoring malformed {faults_lib.PLAN_ENV_VAR}: '
                           f'{e}')
            armed = None
        _env_cache = (value, armed)
        return armed


def is_armed() -> bool:
    return current() is not None


def site_armed(site: str) -> bool:
    """True iff the armed plan (if any) has a fault targeting `site`."""
    armed = current()
    return armed is not None and any(f.site == site
                                     for f in armed.plan.faults)


def inject(site: str, **ctx: Any) -> Optional[object]:
    """The hook instrumented code calls.  No plan armed -> None (fast
    path).  May raise a typed error, sleep, or return :data:`DENY`."""
    armed = current()
    if armed is None:
        return None
    return armed.fire(site, ctx)


def fault_log() -> List[Dict[str, Any]]:
    """This process's fired-fault sequence (empty when nothing armed)."""
    armed = current()
    return list(armed.fault_log) if armed is not None else []


# --------------------------------------------------------------- recording


def chaos_journal() -> events_lib.EventJournal:
    """Shared journal of every injected fault under this SKYTPU_HOME
    (client + emulated-host subprocesses append to the same file)."""
    return events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'chaos.jsonl'))


def chaos_faults_total():
    from skypilot_tpu.observability import metrics  # pylint: disable=import-outside-toplevel
    return metrics.counter('skytpu_chaos_faults_total',
                           'Faults injected by the chaos subsystem',
                           labelnames=('site', 'effect'))
