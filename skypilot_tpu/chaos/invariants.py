"""Invariant checkers: liveness/safety properties replayed over journals.

Each checker takes a ts-ordered list of flight-recorder events (one
journal, or several merged with :func:`merge`) and returns a list of
violation strings — empty means the property held.  They are pure
functions over journal records, so they run identically against a live
scenario, a post-mortem `events/` directory, or a synthetic fixture.

The registry maps names (used by scenarios and the CLI) to checkers:

    recovery_liveness      every preemption_detected is followed by a
                           terminal recovery_end
    gang_abort_coverage    a gang abort accounts for every started rank
                           (victims + the failed rank + clean exits)
    no_excluded_zone_retry the failover loop never re-attempts a zone
                           that already failed within the same launch
    queued_wait_terminal   every queued_wait_start reaches a terminal
                           queued_wait_end (granted or timeout)
    spans_closed           every <name>_start has a matching <name>_end
    resize_monotone_steps  elastic resizes preserve progress: resumes
                           never start below the last ok checkpoint and
                           never regress across resizes
    checkpoint_liveness    every checkpoint_save_start reaches a
                           terminal checkpoint_save_end (no abandoned
                           in-flight save)
    page_pool_balance      every KV page allocated by the serving page
                           pool is eventually freed, and never freed
                           twice
    handoff_consistency    every router-dispatched request completes
                           exactly once (a failed KV handoff degrades
                           to local prefill, never loses or re-runs a
                           request), and every handoff start reaches
                           an ok/fallback end
    drain_no_lost_requests graceful drain: after a replica's lb_retire
                           nothing routes to it, every routed request
                           completes exactly once, and every
                           replica_drain_start reaches a terminal
                           replica_drain_end
    qos_fairness           weighted QoS admission: every qos_request
                           reaches a terminal end (ok/shed/error) and a
                           shed never happens while a lower-weight
                           class holds more in-flight slots (no
                           priority inversion at admission)
    log_spike_terminates   every log_error_spike_start (a replica's
                           WARN/ERROR rate excursion) reaches a later
                           log_error_spike_end — an alert that never
                           clears is a stuck tracker
    batch_exactly_once     the batch-infer ledger commits every
                           (shard, row_idx) at most once, every opened
                           shard's final lifecycle event is an end, and
                           every live weight swap terminates
    no_injections          zero chaos_fault_injected events (clean runs)
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

from skypilot_tpu.observability import event_protocol

Event = Dict[str, Any]

# Lifecycle names and terminal statuses come from the shared paired-
# event protocol table (observability/event_protocol.py): the same
# table `sky lint`'s journal-protocol pass verifies the emit sites
# against, so checkers and emitters cannot drift apart.
_QUEUED_WAIT = event_protocol.BY_NAME['queued_wait']
_CHECKPOINT_SAVE = event_protocol.BY_NAME['checkpoint_save']
_KV_PAGES = event_protocol.BY_NAME['kv_pages']
_KV_HANDOFF = event_protocol.BY_NAME['kv_handoff']
_REPLICA_DRAIN = event_protocol.BY_NAME['replica_drain']
_QOS_REQUEST = event_protocol.BY_NAME['qos_request']
_LOG_ERROR_SPIKE = event_protocol.BY_NAME['log_error_spike']
_BATCH_SHARD = event_protocol.BY_NAME['batch_shard']
_WEIGHT_SWAP = event_protocol.BY_NAME['weight_swap']


def merge(*event_lists: Sequence[Event]) -> List[Event]:
    """Merge journals into one ts-ordered stream (ties keep input
    order, so same-process seq ordering survives)."""
    merged: List[Event] = []
    for events in event_lists:
        merged.extend(events)
    merged.sort(key=lambda e: e.get('ts', 0.0))
    return merged


def _named(events: Sequence[Event], name: str) -> List[Event]:
    return [e for e in events if e.get('event') == name]


# ----------------------------------------------------------------- checkers


def recovery_liveness(events: Sequence[Event]) -> List[str]:
    """Liveness: a detected preemption must reach a recovery_end (any
    status — giving up IS a terminal answer; silence is the bug)."""
    violations = []
    indexed = list(enumerate(events))
    for i, e in indexed:
        if e.get('event') != 'preemption_detected':
            continue
        followed = any(
            later.get('event') == 'recovery_end' and
            later.get('job_id') == e.get('job_id')
            for _, later in indexed[i + 1:])
        if not followed:
            violations.append(
                f'preemption_detected (job {e.get("job_id")}, task '
                f'{e.get("task_id")}) has no subsequent recovery_end')
    return violations


def gang_abort_coverage(events: Sequence[Event]) -> List[str]:
    """Safety: when a gang aborts, victims + the failed rank + ranks
    that had already exited must cover every started rank — a rank left
    running after an abort would burn the slice in a dead collective."""
    violations = []
    started = {e.get('rank') for e in _named(events, 'rank_start')}
    exited = {e.get('rank') for e in _named(events, 'rank_exit')}
    for abort in _named(events, 'gang_abort'):
        covered = set(abort.get('victims') or [])
        covered.add(abort.get('failed_rank'))
        # Ranks that exited on their own before/after the abort are
        # accounted for by their rank_exit records.
        missing = started - covered - exited
        if missing:
            violations.append(
                f'gang_abort covers {sorted(covered)} but ranks '
                f'{sorted(missing)} started and never exited')
    if started - exited:
        violations.append(
            f'ranks {sorted(started - exited)} have rank_start but no '
            f'rank_exit')
    return violations


def no_excluded_zone_retry(events: Sequence[Event]) -> List[str]:
    """Safety: within one launch, a (cloud, region, zone) that failed a
    provision attempt is excluded — re-attempting it wastes the
    failover budget on known-bad capacity."""
    violations = []
    failed: set = set()
    for e in events:
        name = e.get('event')
        key = (e.get('cloud'), e.get('region'), e.get('zone'))
        if name == 'provision_attempt_start' and key in failed:
            violations.append(
                f'provision re-attempted excluded zone {key}')
        elif name == 'provision_attempt_end' and e.get('status') == 'fail':
            failed.add(key)
        elif name == 'launch_start':
            failed.clear()  # a new launch may legitimately retry
    return violations


def queued_wait_terminal(events: Sequence[Event]) -> List[str]:
    """Liveness: every queued-capacity wait reaches a terminal verdict
    within its journal (granted or timeout), never silence."""
    violations = []
    open_waits = 0
    for e in events:
        if e.get('event') == _QUEUED_WAIT.start:
            open_waits += 1
        elif e.get('event') == _QUEUED_WAIT.end:
            open_waits -= 1
            if e.get('status') not in _QUEUED_WAIT.statuses:
                violations.append(
                    f'queued_wait_end has non-terminal status '
                    f'{e.get("status")!r}')
    if open_waits > 0:
        violations.append(
            f'{open_waits} queued_wait_start without queued_wait_end')
    return violations


def spans_closed(events: Sequence[Event]) -> List[str]:
    """Every <name>_start has a later matching <name>_end (crashed
    processes legitimately violate this — apply it to scenarios that
    are supposed to finish cleanly)."""
    violations = []
    open_spans: Dict[str, int] = {}
    for e in events:
        name = e.get('event', '')
        if name.endswith('_start'):
            base = name[:-len('_start')]
            open_spans[base] = open_spans.get(base, 0) + 1
        elif name.endswith('_end'):
            base = name[:-len('_end')]
            open_spans[base] = open_spans.get(base, 0) - 1
    for base, count in sorted(open_spans.items()):
        if count > 0:
            violations.append(f'{count} {base}_start without {base}_end')
    return violations


def resize_monotone_steps(events: Sequence[Event]) -> List[str]:
    """Safety: elastic resizes preserve progress.  Every train_resume
    must start at a step >= the last successfully checkpointed step
    (the restore actually landed), and resume steps never regress
    across resizes — a shrink/expand may recompute at most the tail
    after the newest checkpoint, never travel back in time."""
    violations = []
    last_ok_ckpt = -1
    last_resume = -1
    for e in events:
        name = e.get('event')
        if (name == 'checkpoint_save_end' and e.get('status') == 'ok'
                and e.get('step') is not None):
            last_ok_ckpt = max(last_ok_ckpt, int(e['step']))
        elif name == 'train_resume' and e.get('step') is not None:
            step = int(e['step'])
            if step < last_resume:
                violations.append(
                    f'train_resume at step {step} regressed below the '
                    f'previous resume step {last_resume}')
            if last_ok_ckpt >= 0 and step < last_ok_ckpt:
                violations.append(
                    f'train_resume at step {step} lost checkpointed '
                    f'progress (last ok save was step {last_ok_ckpt})')
            last_resume = max(last_resume, step)
    return violations


def checkpoint_liveness(events: Sequence[Event]) -> List[str]:
    """Liveness: every checkpoint_save_start reaches a terminal
    checkpoint_save_end (ok, or a named failure after retries) — an
    abandoned in-flight save means wait-on-exit/finalize semantics
    broke and the newest "checkpoint" may be a torn write.  (A process
    killed mid-save legitimately violates this — apply it to flows
    that finish under their own power, same caveat as spans_closed.)"""
    violations = []
    open_saves = 0
    for e in events:
        name = e.get('event')
        if name == _CHECKPOINT_SAVE.start:
            open_saves += 1
        elif name == _CHECKPOINT_SAVE.end:
            open_saves -= 1
            if not e.get('status'):
                violations.append(
                    f'checkpoint_save_end for step {e.get("step")} '
                    f'carries no status')
    if open_saves > 0:
        violations.append(
            f'{open_saves} checkpoint_save_start without '
            f'checkpoint_save_end (in-flight save abandoned)')
    return violations


def page_pool_balance(events: Sequence[Event]) -> List[str]:
    """Safety/liveness for the serving KV page pool: every page the
    allocator handed out (`kv_pages_alloc`) is eventually returned
    (`kv_pages_free`), and nothing is freed that was never allocated —
    a leaked page is capacity the replica never gets back; a double
    free is a page two requests would scribble on."""
    violations = []
    outstanding: Dict[int, int] = {}
    for e in events:
        name = e.get('event')
        if name == _KV_PAGES.start:
            for p in (e.get('pages') or []):
                outstanding[p] = outstanding.get(p, 0) + 1
        elif name == _KV_PAGES.end:
            for p in (e.get('pages') or []):
                held = outstanding.get(p, 0)
                if held <= 0:
                    violations.append(
                        f'page {p} freed without a matching alloc')
                else:
                    outstanding[p] = held - 1
    leaked = sorted(p for p, n in outstanding.items() if n > 0)
    if leaked:
        violations.append(
            f'pages {leaked} allocated but never freed (pool leak)')
    return violations


def handoff_consistency(events: Sequence[Event]) -> List[str]:
    """Safety for disaggregated serving: every request the router
    dispatched (`lb_route`) completes EXACTLY once on a replica
    (`serve_request_done`) — a handoff failure may cost latency
    (fallback to local prefill) but never a lost or double-executed
    request — and every `kv_handoff_start` reaches a
    `kv_handoff_end` (ok or fallback; a vanished handoff means the
    router hung between the export and the forward)."""
    violations = []
    routed = [e for e in _named(events, 'lb_route')
              if e.get('request_id')]
    done: Dict[str, int] = {}
    for e in _named(events, 'serve_request_done'):
        rid = e.get('request_id')
        if rid:
            done[rid] = done.get(rid, 0) + 1
    for e in routed:
        rid = e['request_id']
        count = done.get(rid, 0)
        if count == 0:
            violations.append(
                f'request {rid} was routed but never completed on any '
                f'replica (lost across a handoff?)')
        elif count > 1:
            violations.append(
                f'request {rid} completed {count} times '
                f'(double-executed)')
    open_handoffs: Dict[str, int] = {}
    for e in events:
        name = e.get('event')
        if name == _KV_HANDOFF.start:
            rid = e.get('request_id', '?')
            open_handoffs[rid] = open_handoffs.get(rid, 0) + 1
        elif name == _KV_HANDOFF.end:
            rid = e.get('request_id', '?')
            held = open_handoffs.get(rid, 0)
            if held <= 0:
                violations.append(
                    f'kv_handoff_end for {rid} without a start')
            else:
                open_handoffs[rid] = held - 1
            if e.get('status') not in _KV_HANDOFF.statuses:
                violations.append(
                    f'kv_handoff_end for {rid} carries status '
                    f'{e.get("status")!r} (want one of '
                    f'{"/".join(_KV_HANDOFF.statuses)})')
    dangling = [rid for rid, n in open_handoffs.items() if n > 0]
    if dangling:
        violations.append(
            f'kv_handoff_start without kv_handoff_end for {dangling}')
    return violations


def drain_no_lost_requests(events: Sequence[Event]) -> List[str]:
    """Safety for graceful drain: once the LB processed a replica's
    retire nudge (`lb_retire`), no generate is routed there again
    (`lb_route` with that url) until the address is legitimately
    re-opened — a committed role morph (`role_morph_end` with status
    ok/timeout) flips the SAME replica to its new role in place, so
    routes after the commit are the rebalanced fleet working, not a
    drain race (a NEW replica at the same url — tracked via a later
    `replica_drain_start` for a different replica id — is out of scope
    for the scenarios that apply this).  AND every routed request
    still completes exactly once — a drain may cost a retry hop, never
    a lost or double-executed request."""
    violations = []
    retired_at: Dict[str, bool] = {}
    for e in events:
        name = e.get('event')
        if name == 'lb_retire':
            url = e.get('url')
            if url:
                retired_at[url] = True
        elif name == 'role_morph_end':
            # The morph protocol's commit point: the replica re-opened
            # under its new role behind a fresh retire epoch, so the
            # next controller push re-admits the address on purpose.
            url = e.get('url')
            if url and e.get('status') in ('ok', 'timeout'):
                retired_at[url] = False
        elif name == 'lb_route':
            url = e.get('url')
            if url and retired_at.get(url):
                violations.append(
                    f'request {e.get("request_id")} routed to {url} '
                    f'AFTER its retire event (drain raced routing)')
    routed = [e for e in _named(events, 'lb_route')
              if e.get('request_id')]
    done: Dict[str, int] = {}
    for e in _named(events, 'serve_request_done'):
        rid = e.get('request_id')
        if rid:
            done[rid] = done.get(rid, 0) + 1
    for e in routed:
        rid = e['request_id']
        count = done.get(rid, 0)
        if count == 0:
            violations.append(
                f'request {rid} was routed but never completed '
                f'(lost across a drain?)')
        elif count > 1:
            violations.append(
                f'request {rid} completed {count} times '
                f'(double-executed)')
    # Drain lifecycle liveness: every started drain terminates.
    open_drains: Dict[Any, int] = {}
    for e in events:
        name = e.get('event')
        key = (e.get('service'), e.get('replica_id'))
        if name == _REPLICA_DRAIN.start:
            open_drains[key] = open_drains.get(key, 0) + 1
        elif name == _REPLICA_DRAIN.end:
            open_drains[key] = open_drains.get(key, 0) - 1
            if e.get('reason') not in _REPLICA_DRAIN.statuses:
                violations.append(
                    f'replica_drain_end for {key} carries unknown '
                    f'reason {e.get("reason")!r}')
    dangling = [k for k, n in open_drains.items() if n > 0]
    if dangling:
        violations.append(
            f'replica_drain_start without replica_drain_end for '
            f'{dangling}')
    return violations


def qos_fairness(events: Sequence[Event]) -> List[str]:
    """Safety/liveness for weighted QoS admission at the router tier:

    - lifecycle completeness: every `qos_request_start` reaches a
      terminal `qos_request_end` (ok, shed, or error) — a vanished
      admission means the router dropped a request without answering;
    - no priority inversion AT ADMISSION: when a class's request is
      shed, no LOWER-WEIGHT class may be holding more in-flight slots
      than the shed request's class at that moment (the weighted
      shares would then not have been enforced: the heavier class
      starved while the lighter one over-consumed)."""
    violations = []
    weights: Dict[str, int] = {}
    inflight: Dict[str, int] = {}
    open_requests: Dict[str, str] = {}  # request_id -> class
    for e in events:
        name = e.get('event')
        if name == _QOS_REQUEST.start:
            rid = e.get('request_id')
            cls = e.get('qos_class') or 'interactive'
            if e.get('weight') is not None:
                weights[cls] = int(e['weight'])
            if rid:
                open_requests[rid] = cls
            inflight[cls] = inflight.get(cls, 0) + 1
        elif name == _QOS_REQUEST.end:
            rid = e.get('request_id')
            cls = open_requests.pop(rid, None) or \
                e.get('qos_class') or 'interactive'
            status = e.get('status')
            if status not in _QOS_REQUEST.statuses:
                violations.append(
                    f'qos_request_end for {rid} carries status '
                    f'{status!r} (want one of '
                    f'{"/".join(_QOS_REQUEST.statuses)})')
            if status == 'shed':
                # Weighted admission means a class is shed only once
                # it exceeds ITS OWN share — a lower-weight class
                # simultaneously holding MORE in-flight slots would
                # mean the shares were never enforced.
                shed_weight = weights.get(cls, 1)
                for other, count in inflight.items():
                    if other == cls:
                        continue
                    if (weights.get(other, 1) < shed_weight and
                            count > inflight.get(cls, 0)):
                        violations.append(
                            f'priority inversion: {cls} (weight '
                            f'{shed_weight}) shed request {rid} while '
                            f'lower-weight {other} held {count} '
                            f'in-flight (> {inflight.get(cls, 0)})')
            inflight[cls] = max(0, inflight.get(cls, 0) - 1)
    if open_requests:
        violations.append(
            f'{len(open_requests)} qos_request_start without '
            f'qos_request_end: {sorted(open_requests)[:5]}')
    return violations


def log_spike_terminates(events: Sequence[Event]) -> List[str]:
    """Liveness for the fleet log plane: every log_error_spike_start
    (one replica's WARN/ERROR rate above the spike threshold) reaches
    a later log_error_spike_end for the same replica — an error-spike
    alert that never clears means the tracker wedged or the fleet
    never quieted, and either way the operator is staring at a stale
    red light."""
    violations = []
    open_spikes: Dict[Any, int] = {}
    for e in events:
        name = e.get('event')
        key = (e.get('service'), e.get('replica_id'))
        if name == _LOG_ERROR_SPIKE.start:
            open_spikes[key] = open_spikes.get(key, 0) + 1
        elif name == _LOG_ERROR_SPIKE.end:
            held = open_spikes.get(key, 0)
            if held <= 0:
                violations.append(
                    f'log_error_spike_end for {key} without a start')
            else:
                open_spikes[key] = held - 1
    dangling = sorted(k for k, n in open_spikes.items() if n > 0)
    if dangling:
        violations.append(
            f'log_error_spike_start without log_error_spike_end for '
            f'{dangling}')
    return violations


def batch_exactly_once(events: Sequence[Event]) -> List[str]:
    """Exactly-once for the batch-infer ledger: no (shard, row_idx)
    commits twice, every opened shard eventually closes (a driver
    killed mid-shard leaves a dangling batch_shard_start that the
    RESUMED driver must re-open and close — SCOPE_PROCESS pair), and
    every live weight swap terminates."""
    violations = []
    commits: Dict[Tuple[Any, Any], int] = {}
    for e in _named(events, 'batch_row_commit'):
        key = (e.get('shard'), e.get('row_idx'))
        commits[key] = commits.get(key, 0) + 1
    for key, n in sorted(commits.items()):
        if n > 1:
            violations.append(
                f'row (shard={key[0]}, row_idx={key[1]}) committed '
                f'{n} times — the ledger replay re-ran a committed row')
    # Shard lifecycle: the LAST event per shard must be an end (the
    # pre-kill incarnation may legally leave a dangling start; the
    # resumed one re-opens and must close it).
    last_by_shard: Dict[Any, str] = {}
    opened: set = set()
    for e in events:
        name = e.get('event')
        if name in (_BATCH_SHARD.start, _BATCH_SHARD.end):
            last_by_shard[e.get('shard')] = name
            if name == _BATCH_SHARD.start:
                opened.add(e.get('shard'))
    for shard in sorted(opened):
        if last_by_shard.get(shard) != _BATCH_SHARD.end:
            violations.append(
                f'shard {shard}: batch_shard_start never reached a '
                f'final batch_shard_end (the resume never finished it)')
    swaps_open = 0
    for e in events:
        name = e.get('event')
        if name == _WEIGHT_SWAP.start:
            swaps_open += 1
        elif name == _WEIGHT_SWAP.end:
            swaps_open -= 1
            if swaps_open < 0:
                violations.append('weight_swap_end without a start')
    if swaps_open > 0:
        violations.append(
            f'{swaps_open} weight_swap_start without weight_swap_end')
    return violations


def no_injections(events: Sequence[Event]) -> List[str]:
    """With no plan armed, the chaos subsystem must be invisible."""
    injected = _named(events, 'chaos_fault_injected')
    if injected:
        return [f'{len(injected)} chaos_fault_injected events on a run '
                f'that armed no plan']
    return []


CHECKERS: Dict[str, Callable[[Sequence[Event]], List[str]]] = {
    'recovery_liveness': recovery_liveness,
    'gang_abort_coverage': gang_abort_coverage,
    'no_excluded_zone_retry': no_excluded_zone_retry,
    'queued_wait_terminal': queued_wait_terminal,
    'spans_closed': spans_closed,
    'resize_monotone_steps': resize_monotone_steps,
    'checkpoint_liveness': checkpoint_liveness,
    'page_pool_balance': page_pool_balance,
    'handoff_consistency': handoff_consistency,
    'drain_no_lost_requests': drain_no_lost_requests,
    'qos_fairness': qos_fairness,
    'log_spike_terminates': log_spike_terminates,
    'batch_exactly_once': batch_exactly_once,
    'no_injections': no_injections,
}


def check(events: Sequence[Event],
          invariant_names: Sequence[str]) -> List[str]:
    """Run the named checkers; returns all violations, each prefixed
    with the invariant that caught it."""
    violations = []
    for name in invariant_names:
        checker = CHECKERS.get(name)
        if checker is None:
            violations.append(f'{name}: unknown invariant (have '
                              f'{sorted(CHECKERS)})')
            continue
        violations.extend(f'{name}: {v}' for v in checker(events))
    return violations
