"""Chaos subsystem: deterministic fault injection + recovery scenarios.

The north star makes preemptible TPU slices first-class, so preemption,
provision failover, and rank death are the NORMAL operating mode — yet
nothing exercised those paths systematically until a real eviction hit.
This package converts the recovery surface into a regression-tested
contract:

- :mod:`faults` — the seeded :class:`FaultPlan` DSL (JSON / env-loadable
  via ``SKYTPU_CHAOS_PLAN``): faults are described by *site*
  (``provision.create``, ``gang.rank_exec``, ...), trigger (nth-call,
  seeded probability, time window, ctx match) and effect (raise typed
  error, preemption-style kill, added latency, hang, deny).
- :mod:`injector` — the process-global registry with an
  ``inject(site, **ctx)`` hook that is a no-op fast path when no plan is
  armed.  Every injection journals ``chaos_fault_injected`` and bumps
  ``skytpu_chaos_faults_total``.
- :mod:`scenarios` — end-to-end launch→fault→recover flows on the local
  backend, verified against the flight-recorder journal.
- :mod:`invariants` — liveness/safety checks replayed over journals.

CLI: ``sky chaos list`` / ``sky chaos run <scenario> [--seed N]
[--export-trace PATH]``.  See docs/chaos.md.
"""
from skypilot_tpu.chaos.faults import ChaosError
from skypilot_tpu.chaos.faults import Fault
from skypilot_tpu.chaos.faults import FaultPlan
from skypilot_tpu.chaos.faults import SITES
from skypilot_tpu.chaos.injector import DENY
from skypilot_tpu.chaos.injector import arm
from skypilot_tpu.chaos.injector import disarm
from skypilot_tpu.chaos.injector import inject
from skypilot_tpu.chaos.injector import site_armed

__all__ = [
    'ChaosError', 'Fault', 'FaultPlan', 'SITES', 'DENY', 'arm', 'disarm',
    'inject', 'site_armed',
]
