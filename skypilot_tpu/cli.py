"""CLI: the `sky`-equivalent command surface.

Parity: /root/reference/sky/cli.py (launch :1044, exec :1173,
status :1554, queue/logs/cancel/stop/autostop/start/down :1948-2581,
check :2948, show_gpus :3001, groups storage/jobs/serve :3416-4025).
Exposed as `python -m skypilot_tpu.cli` and the `skytpu` entry point.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import click

from skypilot_tpu import __version__
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)


def _parse_env(env: Tuple[str, ...]) -> Dict[str, str]:
    out = {}
    for item in env:
        if '=' in item:
            key, value = item.split('=', 1)
        else:
            key, value = item, os.environ.get(item, '')
        out[key] = value
    return out


def _entrypoint_is_yaml(entrypoint: Optional[str]) -> bool:
    return bool(entrypoint and
                (entrypoint.endswith(('.yaml', '.yml')) or
                 os.path.isfile(os.path.expanduser(entrypoint))))


def _make_task(entrypoint: Optional[str], *, name: Optional[str],
               workdir: Optional[str], cloud: Optional[str],
               region: Optional[str], zone: Optional[str],
               accelerators: Optional[str], cpus: Optional[str],
               memory: Optional[str], instance_type: Optional[str],
               use_spot: Optional[bool], num_nodes: Optional[int],
               env: Tuple[str, ...], command: Optional[str] = None):
    """YAML (or inline command) → Task with CLI overrides applied.

    Parity: reference cli.py:702
    (_make_task_or_dag_from_entrypoint_with_overrides).
    """
    from skypilot_tpu import resources as resources_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import task as task_lib  # pylint: disable=import-outside-toplevel

    if _entrypoint_is_yaml(entrypoint):
        task = task_lib.Task.from_yaml(entrypoint)
    else:
        cmd = command if command is not None else entrypoint
        task = task_lib.Task(run=cmd)

    if name is not None:
        task.name = name
    if workdir is not None:
        task.workdir = workdir
    if num_nodes is not None:
        task.num_nodes = num_nodes
    if env:
        task.update_envs(_parse_env(env))

    override: Dict[str, Any] = {}
    if cloud is not None:
        override['cloud'] = cloud
    if region is not None:
        override['region'] = region
    if zone is not None:
        override['zone'] = zone
    if accelerators is not None:
        override['accelerators'] = accelerators
    if cpus is not None:
        override['cpus'] = cpus
    if memory is not None:
        override['memory'] = memory
    if instance_type is not None:
        override['instance_type'] = instance_type
    if use_spot is not None:
        override['use_spot'] = use_spot
    if override:
        if task.resources:
            task.set_resources(
                {r.copy(**override) for r in task.resources})
        else:
            task.set_resources(resources_lib.Resources(**override))
    return task


_TASK_OPTIONS = [
    click.option('--name', '-n', default=None, help='Task/cluster name.'),
    click.option('--workdir', default=None,
                 help='Directory synced to all hosts.'),
    click.option('--cloud', default=None,
                 help='Infra to use (gcp | local).'),
    click.option('--region', default=None),
    click.option('--zone', default=None),
    click.option('--gpus', '--accelerators', 'accelerators', default=None,
                 help="Accelerators, e.g. 'tpu-v5e-8' or 'A100:8'."),
    click.option('--cpus', default=None),
    click.option('--memory', default=None),
    click.option('--instance-type', '-t', default=None),
    click.option('--use-spot/--no-use-spot', 'use_spot', default=None),
    click.option('--num-nodes', type=int, default=None,
                 help='Number of slices/nodes.'),
    click.option('--env', multiple=True,
                 help='Env var KEY=VALUE (repeatable).'),
]


def _add_options(options):

    def deco(f):
        for option in reversed(options):
            f = option(f)
        return f

    return deco


# Shell completion (parity: reference cli.py:345
# --install-shell-completion).  Click emits the completion script
# itself (_SKYTPU_COMPLETE=<shell>_source skytpu); these options wire
# it into the user's rc file / completions dir.
_COMPLETION_SETUP = {
    'bash': ('~/.bashrc',
             'eval "$(_SKYTPU_COMPLETE=bash_source skytpu)"'),
    'zsh': ('~/.zshrc',
            'eval "$(_SKYTPU_COMPLETE=zsh_source skytpu)"'),
    'fish': ('~/.config/fish/completions/skytpu.fish',
             '_SKYTPU_COMPLETE=fish_source skytpu | source'),
}
_COMPLETION_MARK = '# skytpu shell completion'


def _install_completion(ctx, param, value):
    del param
    if not value or ctx.resilient_parsing:
        return
    rc_path, line = _COMPLETION_SETUP[value]
    path = os.path.expanduser(rc_path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    content = ''
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            content = f.read()
    if _COMPLETION_MARK in content:
        click.echo(f'Shell completion already installed in {rc_path}.')
    else:
        with open(path, 'a', encoding='utf-8') as f:
            f.write(f'\n{_COMPLETION_MARK}\n{line}\n')
        click.echo(f'Installed {value} completion in {rc_path}; '
                   f'restart your shell to activate.')
    ctx.exit()


def _uninstall_completion(ctx, param, value):
    del param
    if not value or ctx.resilient_parsing:
        return
    rc_path, _ = _COMPLETION_SETUP[value]
    path = os.path.expanduser(rc_path)
    removed = False
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            lines = f.read().splitlines()
        kept, skip_next = [], False
        for line in lines:
            if skip_next:
                skip_next = False
                continue
            if line.strip() == _COMPLETION_MARK:
                removed = True
                skip_next = True  # the eval line that follows the mark
                # Also drop the blank separator install wrote, so
                # install/uninstall cycles don't accumulate blanks.
                if kept and not kept[-1].strip():
                    kept.pop()
                continue
            kept.append(line)
        if removed:
            with open(path, 'w', encoding='utf-8') as f:
                f.write('\n'.join(kept) + ('\n' if kept else ''))
    if removed:
        click.echo(f'Removed skytpu completion from {rc_path}.')
    else:
        click.echo(f'No skytpu completion found in {rc_path}; '
                   'nothing removed.')
    ctx.exit()


def _complete_cluster_name(ctx, param, incomplete):
    """Cluster-name completion for every cluster-taking command."""
    del ctx, param
    try:
        from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
        return [r['name'] for r in global_user_state.get_clusters()
                if r['name'].startswith(incomplete)]
    except Exception:  # pylint: disable=broad-except
        return []  # completion must never crash the shell


@click.group()
# Explicit version: click's package introspection fails when running
# from a source tree (PYTHONPATH) rather than an installed wheel.
@click.version_option(version=__version__, message='%(version)s')
@click.option('--install-shell-completion',
              type=click.Choice(sorted(_COMPLETION_SETUP)),
              callback=_install_completion, expose_value=False,
              is_eager=True,
              help='Install shell tab-completion and exit.')
@click.option('--uninstall-shell-completion',
              type=click.Choice(sorted(_COMPLETION_SETUP)),
              callback=_uninstall_completion, expose_value=False,
              is_eager=True,
              help='Remove shell tab-completion and exit.')
def cli():
    """skypilot_tpu: run AI workloads on TPU slices, anywhere."""
    # Crash-safe orphan cleanup: kill daemons whose state dir vanished
    # (e.g. a kill -9'd run left skylets behind).  Cheap no-op normally.
    from skypilot_tpu.utils import daemon_registry  # pylint: disable=import-outside-toplevel
    daemon_registry.reap_stale()


# ------------------------------------------------------------------ launch


@cli.command()
@click.argument('entrypoint', required=False)
@click.option('--cluster', '-c', default=None, help='Cluster name.',
              shell_complete=_complete_cluster_name)
@click.option('--dryrun', is_flag=True, default=False)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True, default=False,
              help='Tear down the cluster when the job finishes.')
@click.option('--retry-until-up', '-r', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--no-setup', is_flag=True, default=False)
@_add_options(_TASK_OPTIONS)
def launch(entrypoint, cluster, dryrun, detach_run,
           idle_minutes_to_autostop, down, retry_until_up, yes, no_setup,
           **task_args):
    """Launch a task (YAML file or inline command) on a (new) cluster."""
    from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
    task = _make_task(entrypoint, **task_args)
    if not yes and not dryrun:
        click.confirm(f'Launching task on cluster '
                      f'{cluster or "(auto-named)"}. Proceed?',
                      default=True, abort=True)
    try:
        job_id = execution.launch(
            task, cluster_name=cluster, dryrun=dryrun,
            detach_run=detach_run, down=down,
            idle_minutes_to_autostop=idle_minutes_to_autostop,
            retry_until_up=retry_until_up, no_setup=no_setup)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(common_utils.format_exception(e))
    if job_id is not None:
        click.echo(f'Job submitted with ID: {job_id}')


@cli.command(name='exec')
@click.argument('cluster', shell_complete=_complete_cluster_name)
@click.argument('entrypoint', required=False)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@_add_options(_TASK_OPTIONS)
def exec_cmd(cluster, entrypoint, detach_run, **task_args):
    """Run a task on an existing cluster (skip provision/setup)."""
    from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
    task = _make_task(entrypoint, **task_args)
    try:
        job_id = execution.exec(task, cluster_name=cluster,
                                detach_run=detach_run)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(common_utils.format_exception(e))
    if job_id is not None:
        click.echo(f'Job submitted with ID: {job_id}')


# ------------------------------------------------------------------ status


@cli.command()
@click.option('--refresh', '-r', is_flag=True, default=False,
              help='Re-query live cluster status from the provider.')
@click.option('--verbose', '-v', is_flag=True, default=False,
              help='Show the last launch stage-runtime decomposition.')
@click.option('--events', 'show_events', is_flag=True, default=False,
              help='Print the control-plane event timeline (flight '
                   'recorder) for the given cluster(s).')
@click.option('--export-trace', 'export_trace', default=None,
              help='With --events: also write the events as a '
                   'Chrome-trace JSON to this path.')
@click.argument('clusters', nargs=-1, shell_complete=_complete_cluster_name)
def status(refresh, verbose, show_events, export_trace, clusters):
    """Show clusters."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import usage_lib  # pylint: disable=import-outside-toplevel
    if show_events:
        if not clusters:
            raise click.UsageError(
                'status --events requires at least one cluster name.')
        _print_cluster_events(list(clusters), export_trace)
        return
    records = core.status(cluster_names=list(clusters) or None,
                          refresh=refresh)
    if not records:
        click.echo('No existing clusters.')
        return
    rows = []
    for r in records:
        handle = r.get('handle')
        resources_str = '-'
        if handle is not None and getattr(handle, 'launched_resources',
                                          None) is not None:
            resources_str = str(handle.launched_resources)
        launch_rec = r.get('last_launch')
        ttfs = (f'{launch_rec["time_to_first_step"]:.1f}s'
                if launch_rec else '-')
        rows.append((r['name'], resources_str, str(r['status'].value),
                     r.get('autostop', '-'), ttfs))
    _print_table(['NAME', 'RESOURCES', 'STATUS', 'AUTOSTOP',
                  'TIME-TO-FIRST-STEP'], rows)
    if verbose:
        for r in records:
            if r.get('last_launch'):
                click.echo(f'\n{r["name"]}: '
                           + usage_lib.format_decomposition(
                               r['last_launch']))


def _print_cluster_events(clusters: List[str],
                          export_trace: Optional[str]) -> None:
    """`status --events`: render each cluster's flight-recorder journal
    as a readable timeline (and optionally a Chrome trace)."""
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    all_events = []
    for name in clusters:
        events = events_lib.cluster_events(name)
        if not events:
            click.echo(f'{name}: no recorded events.')
            continue
        click.echo(f'Events for cluster {name} '
                   f'({len(events)} recorded):')
        for line in events_lib.format_timeline(events):
            click.echo(f'  {line}')
        all_events.extend(events)
    if export_trace and all_events:
        events_lib.export_chrome_trace(all_events, export_trace)
        click.echo(f'Chrome trace written to {export_trace} '
                   '(open in chrome://tracing or Perfetto).')


def _print_table(headers: List[str], rows: List[tuple]) -> None:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    fmt = '  '.join(f'{{:<{w}}}' for w in widths)
    click.echo(fmt.format(*headers))
    for row in rows:
        click.echo(fmt.format(*[str(c) for c in row]))


# ------------------------------------------------------- lifecycle verbs


@cli.command()
@click.argument('cluster', shell_complete=_complete_cluster_name)
@click.argument('port', required=False, type=int)
def endpoints(cluster, port):
    """Show a cluster's exposed port endpoints.

    Parity: reference `sky status --endpoints` / core.endpoints."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    try:
        eps = core.endpoints(cluster, port=port)
    except Exception as e:  # pylint: disable=broad-except
        raise click.ClickException(str(e)) from e
    for p, addr in sorted(eps.items()):
        click.echo(f'{p}: http://{addr}')


@cli.command()
@click.argument('clusters', nargs=-1, required=True,
                shell_complete=_complete_cluster_name)
@click.option('--yes', '-y', is_flag=True, default=False)
def stop(clusters, yes):
    """Stop cluster(s) (restartable with `start`)."""
    _lifecycle('stop', clusters, yes)


@cli.command()
@click.argument('clusters', nargs=-1, required=True,
                shell_complete=_complete_cluster_name)
@click.option('--yes', '-y', is_flag=True, default=False)
def start(clusters, yes):
    """Restart stopped cluster(s)."""
    _lifecycle('start', clusters, yes)


@cli.command()
@click.argument('clusters', nargs=-1, required=True,
                shell_complete=_complete_cluster_name)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--purge', is_flag=True, default=False)
def down(clusters, yes, purge):
    """Terminate cluster(s)."""
    _lifecycle('down', clusters, yes, purge=purge)


def _lifecycle(verb: str, clusters, yes: bool, **kwargs) -> None:
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
    names: List[str] = []
    for pattern in clusters:
        names.extend(global_user_state.get_glob_cluster_names(pattern))
    names = sorted(set(names))
    if not names:
        click.echo(f'No clusters match {clusters}.')
        return
    if not yes:
        click.confirm(f'{verb} cluster(s) {", ".join(names)}?',
                      default=True, abort=True)
    for name in names:
        try:
            getattr(core, verb)(name, **kwargs)
            click.echo(f'{verb}: {name} done.')
        except exceptions.SkyTpuError as e:
            click.echo(f'{verb}: {name} failed: '
                       f'{common_utils.format_exception(e)}', err=True)


@cli.command()
@click.argument('cluster', shell_complete=_complete_cluster_name)
@click.option('--idle-minutes', '-i', type=int, required=True)
@click.option('--down', is_flag=True, default=False)
@click.option('--cancel', is_flag=True, default=False)
def autostop(cluster, idle_minutes, down, cancel):
    """Schedule stop/down after idle minutes (-1 or --cancel clears)."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    if cancel:
        idle_minutes = -1
    core.autostop(cluster, idle_minutes, down=down)
    click.echo('Autostop updated.')


# ----------------------------------------------------------- job verbs


@cli.command()
@click.argument('cluster', shell_complete=_complete_cluster_name)
@click.option('--skip-finished', '-s', is_flag=True, default=False)
def queue(cluster, skip_finished):
    """Show the cluster's job queue."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    jobs = core.queue(cluster, all_jobs=not skip_finished)
    rows = [(j['job_id'], j['job_name'], j.get('username', '-'),
             j['status']) for j in jobs]
    _print_table(['ID', 'NAME', 'USER', 'STATUS'], rows)


@cli.command()
@click.argument('cluster', shell_complete=_complete_cluster_name)
@click.argument('job_id', required=False, type=int)
@click.option('--no-follow', is_flag=True, default=False)
def logs(cluster, job_id, no_follow):
    """Tail a job's logs."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    core.tail_logs(cluster, job_id, follow=not no_follow)


@cli.command()
@click.argument('cluster', shell_complete=_complete_cluster_name)
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', '-a', 'all_jobs', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def cancel(cluster, job_ids, all_jobs, yes):
    """Cancel job(s) on a cluster."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    if not job_ids and not all_jobs:
        raise click.UsageError('Provide job ids or --all.')
    if not yes:
        what = 'all jobs' if all_jobs else f'jobs {list(job_ids)}'
        click.confirm(f'Cancel {what} on {cluster}?', default=True,
                      abort=True)
    core.cancel(cluster, job_ids=list(job_ids) or None,
                all_jobs=all_jobs)


# ------------------------------------------------------------ cost report


@cli.command(name='cost-report')
def cost_report():
    """Accumulated cost + launch-overhead per cluster (incl. history).

    Parity: reference `sky cost-report`; adds the time-to-first-step
    column (the north-star denominator, usage_lib).
    """
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    records = core.cost_report()
    if not records:
        click.echo('No clusters in history.')
        return
    rows = []
    for r in records:
        duration_h = (r.get('duration', 0) or 0) / 3600.0
        ttfs = (f'{r["time_to_first_step"]:.1f}s'
                if r.get('time_to_first_step') else '-')
        status = r.get('status')
        rows.append((r.get('name', '-'), f'{duration_h:.1f}h',
                     f'${r.get("total_cost", 0.0):.2f}', ttfs,
                     status.value if status else 'TERMINATED'))
    _print_table(['NAME', 'UPTIME', 'COST', 'TIME-TO-FIRST-STEP',
                  'STATUS'], rows)


# ------------------------------------------------------------------ check


@cli.command()
def check():
    """Verify credentials for each infra and enable the usable ones."""
    # NB: `skypilot_tpu.check` the *attribute* is the function (rebound
    # by the package __init__), so import it from the module directly.
    from skypilot_tpu.check import check as check_fn  # pylint: disable=import-outside-toplevel
    check_fn()


@cli.command(name='show-tpus')
@click.option('--all', '-a', 'show_all', is_flag=True, default=False)
def show_tpus(show_all):
    """List TPU (and GPU) offerings with pricing."""
    from skypilot_tpu import catalog  # pylint: disable=import-outside-toplevel
    entries = catalog.list_accelerators()
    rows = []
    for name, infos in sorted(entries.items()):
        for info in infos:
            if not show_all and not name.startswith('tpu'):
                continue
            rows.append((name, info.accelerator_count, info.cloud,
                         info.region or '-',
                         f'{info.price:.2f}' if info.price else '-',
                         f'{info.spot_price:.2f}'
                         if info.spot_price else '-'))
    _print_table(
        ['ACCELERATOR', 'COUNT', 'CLOUD', 'REGION', '$/HR', 'SPOT $/HR'],
        rows)


# ------------------------------------------------------------- jobs group


@cli.group(name='jobs')
def jobs_group():
    """Managed jobs with auto-recovery."""


@jobs_group.command(name='launch')
@click.argument('entrypoint', required=False)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
@_add_options(_TASK_OPTIONS)
def jobs_launch(entrypoint, detach_run, yes, **task_args):
    """Launch a managed job (supervised, auto-recovered).

    A multi-document YAML is a chain pipeline: each stage runs on its
    own cluster in order, supervised end-to-end (parity: reference
    managed-jobs pipelines)."""
    from skypilot_tpu import jobs  # pylint: disable=import-outside-toplevel
    entry = _load_chain_if_multidoc(entrypoint, task_args)
    if entry is None:
        entry = _make_task(entrypoint, **task_args)
    if not yes:
        click.confirm('Launch managed job?', default=True, abort=True)
    job_id = jobs.launch(entry, detach_run=detach_run)
    click.echo(f'Managed job ID: {job_id}')


def _load_chain_if_multidoc(entrypoint, task_args):
    """-> Dag when `entrypoint` is a multi-document YAML, else None."""
    if not _entrypoint_is_yaml(entrypoint):
        return None
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import dag_utils  # pylint: disable=import-outside-toplevel
    try:
        docs = [d for d in common_utils.read_yaml_all(
            os.path.expanduser(entrypoint)) if d]
    except OSError:
        return None
    if len(docs) <= 1:
        return None
    overrides = {k: v for k, v in task_args.items()
                 if v not in (None, ())}
    if overrides:
        raise click.UsageError(
            f'CLI task overrides {sorted(overrides)} cannot apply to a '
            'multi-stage pipeline YAML; set per-stage fields in the '
            'file instead.')
    return dag_utils.load_chain_dag_from_configs(docs)


@jobs_group.command(name='queue')
def jobs_queue():
    """List managed jobs."""
    from skypilot_tpu import jobs  # pylint: disable=import-outside-toplevel
    records = jobs.queue()
    rows = []
    for r in records:
        # WHY the job is (or last was) recovering, not just that it is.
        reason = r.get('last_recovery_reason') or r.get(
            'failure_reason') or '-'
        # Batch-infer drivers report shard-ledger progress through
        # jobs/state.py (same plumbing as the recovery reason).
        progress = r.get('batch_progress') or '-'
        rows.append((r['job_id'], r['task_id'], r['job_name'],
                     r['status'], r['recovery_count'], progress,
                     common_utils.truncate_long_string(str(reason), 48)))
    _print_table(['ID', 'TASK', 'NAME', 'STATUS', 'RECOVERIES',
                  'PROGRESS', 'REASON'], rows)


@jobs_group.command(name='events')
@click.argument('job_id', type=int)
@click.option('--export-trace', 'export_trace', default=None,
              help='Also write the events as a Chrome-trace JSON to '
                   'this path.')
def jobs_events(job_id, export_trace):
    """Show a managed job's control-plane event timeline.

    The flight recorder journals every launch attempt, preemption
    detection, and recovery span the controller performed for this job;
    this renders them as a timeline (post-mortemable after the
    controller exits)."""
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    events = events_lib.job_events(job_id)
    if not events:
        click.echo(f'Managed job {job_id}: no recorded events.')
        return
    click.echo(f'Events for managed job {job_id} '
               f'({len(events)} recorded):')
    for line in events_lib.format_timeline(events):
        click.echo(f'  {line}')
    if export_trace:
        events_lib.export_chrome_trace(events, export_trace)
        click.echo(f'Chrome trace written to {export_trace} '
                   '(open in chrome://tracing or Perfetto).')


@jobs_group.command(name='cancel')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', '-a', 'all_jobs', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_cancel(job_ids, all_jobs, yes):
    """Cancel managed job(s)."""
    from skypilot_tpu import jobs  # pylint: disable=import-outside-toplevel
    if not job_ids and not all_jobs:
        raise click.UsageError('Provide job ids or --all.')
    if not yes:
        click.confirm('Cancel managed job(s)?', default=True, abort=True)
    cancelled = jobs.cancel(list(job_ids) or None, all_jobs=all_jobs)
    click.echo(f'Cancellation requested for: {cancelled}')


@jobs_group.command(name='logs')
@click.argument('job_id', required=False, type=int)
@click.option('--no-follow', is_flag=True, default=False)
def jobs_logs(job_id, no_follow):
    """Tail a managed job's logs."""
    from skypilot_tpu import jobs  # pylint: disable=import-outside-toplevel
    jobs.tail_logs(job_id, follow=not no_follow)


@jobs_group.command(name='dashboard')
@click.option('--refresh', '-r', 'refresh_every', type=float, default=0,
              help='Redraw every N seconds (0 = print once and exit).')
def jobs_dashboard(refresh_every):
    """Live text dashboard of managed jobs.

    Parity: reference sky/jobs/dashboard (web) — rendered as a
    terminal table: status mix, per-job state, recoveries, age.
    """
    import collections  # pylint: disable=import-outside-toplevel
    import datetime  # pylint: disable=import-outside-toplevel
    import time as time_lib  # pylint: disable=import-outside-toplevel

    from skypilot_tpu import jobs  # pylint: disable=import-outside-toplevel

    def _render():
        records = jobs.queue()
        by_status = collections.Counter(r['status'] for r in records)
        summary = '  '.join(f'{s}: {n}'
                            for s, n in sorted(by_status.items()))
        now = time_lib.time()
        rows = []
        for r in records:
            age = '-'
            if r.get('submitted_at'):
                age = str(datetime.timedelta(
                    seconds=int(now - r['submitted_at'])))
            rows.append((r['job_id'], r['task_id'], r['job_name'],
                         r['status'], r['recovery_count'],
                         r.get('cluster_name') or '-', age))
        click.echo(f'Managed jobs — {len(records)} total'
                   + (f'  ({summary})' if summary else ''))
        _print_table(
            ['ID', 'TASK', 'NAME', 'STATUS', 'RECOVERIES', 'CLUSTER',
             'AGE'], rows)

    if refresh_every <= 0:
        _render()
        return
    try:
        while True:
            click.clear()
            _render()
            time_lib.sleep(refresh_every)
    except KeyboardInterrupt:
        pass


# ------------------------------------------------------ batch-infer group


@cli.group(name='batch-infer')
def batch_infer_group():
    """Offline bulk inference riding the serving QoS floor."""


@batch_infer_group.command(name='launch')
@click.option('--input', 'input_path', required=True,
              help='Source JSONL: one request object per line '
                   '("prompt" string or "prompt_ids" list, plus '
                   'optional per-row overrides).')
@click.option('--endpoint', required=True,
              help='Serving front door (LB or replica) URL.')
@click.option('--run-dir', default=None,
              help='Manifest/run directory '
                   '(default: <input>.batchrun).')
@click.option('--num-shards', type=int, default=8)
@click.option('--max-new-tokens', type=int, default=16)
@click.option('--inflight', type=int, default=None,
              help='Bounded in-flight rows '
                   '(default: SKYTPU_BATCH_INFLIGHT or 4).')
@click.option('--managed', is_flag=True, default=False,
              help='Submit the driver as a managed job (a dead driver '
                   'is relaunched and resumes off the ledger) instead '
                   'of running it inline.')
def batch_infer_launch(input_path, endpoint, run_dir, num_shards,
                       max_new_tokens, inflight, managed):
    """Shard INPUT into a run directory and drive it through ENDPOINT.

    Rows flow as QoS class `batch`: the router's weighted admission
    keeps interactive traffic at its floor and sheds batch overflow
    with 429 + Retry-After, which the driver honors.  The run
    directory's shard ledger makes any restart a resume — committed
    rows never re-run, and the final rewrite dedupes half-committed
    ones (exactly-once outputs)."""
    import json as json_lib  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.batch import manifest as manifest_lib  # pylint: disable=import-outside-toplevel
    run_dir = run_dir or input_path + '.batchrun'
    manifest = manifest_lib.build_manifest(input_path, run_dir,
                                           num_shards=num_shards)
    click.echo(f'Manifest: {manifest.total_rows} rows in '
               f'{manifest.num_shards} shards under {run_dir}')
    if managed:
        import skypilot_tpu as sky  # pylint: disable=import-outside-toplevel
        from skypilot_tpu import jobs  # pylint: disable=import-outside-toplevel
        cmd = (f'python -m skypilot_tpu.batch.runner '
               f'--manifest-dir {run_dir} --endpoint {endpoint} '
               f'--max-new-tokens {max_new_tokens}')
        if inflight:
            cmd += f' --inflight {inflight}'
        task = sky.Task(name='batch-infer', run=cmd)
        job_id = jobs.launch(task)
        click.echo(f'Managed job ID: {job_id} (watch `sky jobs queue` '
                   f'PROGRESS, or `sky batch-infer status {run_dir}`)')
        return
    from skypilot_tpu.batch import runner as runner_lib  # pylint: disable=import-outside-toplevel
    job = runner_lib.BatchInferJob(run_dir, endpoint,
                                   max_new_tokens=max_new_tokens,
                                   inflight=inflight)
    click.echo(json_lib.dumps(job.run()))


@batch_infer_group.command(name='status')
@click.argument('run_dir')
def batch_infer_status(run_dir):
    """Show a run's shard-ledger progress."""
    from skypilot_tpu.batch import manifest as manifest_lib  # pylint: disable=import-outside-toplevel
    manifest = manifest_lib.Manifest(run_dir)
    progress = manifest_lib.ShardLedger(run_dir).progress(manifest)
    click.echo(
        f'{progress["shards_done"]}/{progress["shards_total"]} shards '
        f'({progress["rows_done"]}/{progress["rows_total"]} rows)')


@batch_infer_group.command(name='resume')
@click.argument('run_dir')
@click.option('--endpoint', required=True,
              help='Serving front door (LB or replica) URL.')
@click.option('--max-new-tokens', type=int, default=16)
@click.option('--inflight', type=int, default=None)
def batch_infer_resume(run_dir, endpoint, max_new_tokens, inflight):
    """Resume a dead run off its ledger.

    Committed rows never re-run; rows cut mid-commit re-run and dedupe
    on the final rewrite.  Resuming a finished run is an idempotent
    re-verification of the outputs."""
    import json as json_lib  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.batch import runner as runner_lib  # pylint: disable=import-outside-toplevel
    job = runner_lib.BatchInferJob(run_dir, endpoint,
                                   max_new_tokens=max_new_tokens,
                                   inflight=inflight)
    click.echo(json_lib.dumps(job.run()))


# ------------------------------------------------------------ serve group


@cli.group(name='serve')
def serve_group():
    """Autoscaled serving."""


@serve_group.command(name='up')
@click.argument('entrypoint')
@click.option('--service-name', '-n', default=None)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_up(entrypoint, service_name, yes):
    """Start a service from a task YAML with a `service:` section."""
    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import task as task_lib  # pylint: disable=import-outside-toplevel
    task = task_lib.Task.from_yaml(entrypoint)
    if not yes:
        click.confirm('Start service?', default=True, abort=True)
    name, endpoint = serve.up(task, service_name)
    click.echo(f'Service {name} starting; endpoint: {endpoint}')


@serve_group.command(name='update')
@click.argument('service_name')
@click.argument('entrypoint')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_update(service_name, entrypoint, yes):
    """Roll the service over to a new task YAML."""
    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import task as task_lib  # pylint: disable=import-outside-toplevel
    task = task_lib.Task.from_yaml(entrypoint)
    if not yes:
        click.confirm(f'Update service {service_name}?', default=True,
                      abort=True)
    version = serve.update(task, service_name)
    click.echo(f'Service {service_name} updating to version {version}.')


@serve_group.command(name='status')
@click.argument('service_names', nargs=-1)
@click.option('--metrics', 'show_metrics', is_flag=True, default=False,
              help='Scrape /metrics from each READY replica and show '
                   'live engine telemetry (decode tokens/s, slots, '
                   'queue, TTFT/ITL p50/p99).')
def serve_status(service_names, show_metrics):
    """Show services and their replicas."""
    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel
    records = serve.status(list(service_names) or None)
    if not records:
        click.echo('No services.')
        return
    rows = []
    for r in records:
        ready = sum(1 for rep in r['replicas']
                    if rep['status'] == 'READY')
        # Multi-host slice replicas: surface the fleet's host footprint
        # (sum of per-replica num_hosts; '2x2' reads "2 replicas x 2
        # hosts" when uniform, else the plain total).
        host_counts = [rep.get('num_hosts') or 1 for rep in r['replicas']]
        if host_counts and len(set(host_counts)) == 1:
            hosts = (f'{len(host_counts)}x{host_counts[0]}'
                     if host_counts[0] > 1 else str(len(host_counts)))
        else:
            hosts = str(sum(host_counts)) if host_counts else '-'
        rows.append((r['name'], r['status'], r['version'],
                     f'{ready}/{len(r["replicas"])}', hosts,
                     r.get('load_balancer_port') or '-'))
    _print_table(['NAME', 'STATUS', 'VERSION', 'READY', 'HOSTS',
                  'LB PORT'], rows)
    if show_metrics:
        _serve_metrics_table(records)


def _hist_quantile(parsed, name: str, q: float):
    """Thin import: the real implementation (with linear interpolation
    inside the winning bucket) lives in observability/metrics.py as
    `histogram_quantile`, next to the exposition parser it consumes."""
    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    return metrics_lib.histogram_quantile(parsed, name, q)


def _rank_lag(parsed) -> str:
    """Tick lag across a slice replica's ranks, from the
    skytpu_slice_rank_ticks_total{rank} counter: max - min ticks.  A
    growing lag names a degraded-but-alive rank (visible during drains
    and rolling updates, before the gang actually fails)."""
    ticks = parsed.get('skytpu_slice_rank_ticks_total') or {}
    per_rank = {}
    for labels, value in ticks.items():
        rank = dict(labels).get('rank')
        if rank is not None:
            per_rank[rank] = per_rank.get(rank, 0) + value
    if len(per_rank) < 2:
        return '-'
    return f'{int(max(per_rank.values()) - min(per_rank.values()))}'


def _serve_lb_table(records) -> None:
    """One row per service's load balancer, scraped from its
    /lb/metrics: controller-sync staleness (a dead controller shows up
    HERE, before replicas start flapping unseen)."""
    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import http_protocol  # pylint: disable=import-outside-toplevel
    rows = []
    for r in records:
        lb_port = r.get('load_balancer_port')
        if not lb_port:
            continue
        try:
            resp = requests.get(
                f'http://127.0.0.1:{lb_port}'
                f'{http_protocol.LB_METRICS}', timeout=5)
            resp.raise_for_status()
            parsed = metrics_lib.parse_exposition(resp.text)
            age = sum((parsed.get(
                'skytpu_lb_controller_sync_age_seconds') or {})
                .values())
            retries = sum((parsed.get('skytpu_lb_retries_total')
                           or {}).values())
            retired = sum((parsed.get('skytpu_lb_retired_total')
                           or {}).values())
            rows.append((r['name'], lb_port, f'{age:.0f}s',
                         int(retries), int(retired)))
        except (requests.RequestException, ValueError) as e:
            rows.append((r['name'], lb_port,
                         f'scrape failed: {e}', '-', '-'))
    if not rows:
        return
    click.echo('')
    _print_table(['SERVICE', 'LB PORT', 'SYNC AGE', 'RETRIES',
                  'RETIRED'], rows)


def _serve_router_table(records) -> None:
    """One row per router-tier instance, from the skytpu_router_*
    series on each registered router port's /lb/metrics.  In-process
    tiers share one metric registry (every port exposes every
    instance's series, distinguished by the `router` label), so rows
    are unioned across ports by that label."""
    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import http_protocol  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import serve_state  # pylint: disable=import-outside-toplevel
    rows = []
    for r in records:
        ports = serve_state.get_router_ports(r)
        per_router = {}
        for port in ports:
            try:
                resp = requests.get(
                    f'http://127.0.0.1:{port}'
                    f'{http_protocol.LB_METRICS}', timeout=5)
                resp.raise_for_status()
                parsed = metrics_lib.parse_exposition(resp.text)
            except (requests.RequestException, ValueError):
                continue

            def by_router(name, parsed=parsed):
                out = {}
                for labels, value in (parsed.get(name) or {}).items():
                    rid = dict(labels).get('router')
                    if rid is not None:
                        out[rid] = value
                return out

            affinity = {}
            for labels, value in (parsed.get(
                    'skytpu_router_affinity_total') or {}).items():
                d = dict(labels)
                affinity.setdefault(d.get('router'), {})[
                    d.get('outcome')] = value
            for name, values in (
                    ('qps', by_router('skytpu_router_qps')),
                    ('inflight',
                     by_router('skytpu_router_inflight')),
                    ('sync_age',
                     by_router('skytpu_router_sync_age_seconds')),
                    ('requests',
                     by_router('skytpu_router_requests_total'))):
                for rid, value in values.items():
                    per_router.setdefault(rid, {})[name] = value
            for rid, outcomes in affinity.items():
                per_router.setdefault(rid, {})['affinity'] = outcomes
        for rid in sorted(per_router):
            stats = per_router[rid]
            outcomes = stats.get('affinity') or {}
            routed = sum(outcomes.values())
            share = (f'{outcomes.get("hit", 0) / routed:.0%}hit'
                     if routed else '-')
            age = stats.get('sync_age')
            rows.append((r['name'], rid,
                         f'{stats.get("qps", 0):g}',
                         int(stats.get('inflight', 0)),
                         share,
                         '-' if age is None else f'{age:.0f}s',
                         int(stats.get('requests', 0))))
    if not rows:
        return
    click.echo('')
    _print_table(['SERVICE', 'ROUTER', 'QPS', 'INFLIGHT', 'AFFINITY',
                  'SYNC AGE', 'REQUESTS'], rows)


def _serve_metrics_table(records) -> None:
    """One row per READY replica, scraped live from GET /metrics
    (observability/metrics.py exposition on the model server)."""
    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import http_protocol  # pylint: disable=import-outside-toplevel

    def fmt_ms(seconds):
        return '-' if seconds is None else (
            'inf' if seconds == float('inf')
            else f'{seconds * 1e3:g}ms')

    rows = []
    for r in records:
        for rep in r['replicas']:
            if rep['status'] != 'READY' or not rep.get('url'):
                continue
            url = rep['url']
            role = rep.get('role') or 'mixed'
            num_hosts = rep.get('num_hosts') or 1
            # LIVE role from the replica's health payload: a morphed
            # replica (dynamic co-location) must never render its
            # launch-time role; the serve_state record is the
            # fallback when the probe fails.
            try:
                health = requests.get(url + '/', timeout=5).json()
                role = health.get('role') or role
            except (requests.RequestException, ValueError):
                pass
            try:
                resp = requests.get(url + http_protocol.METRICS,
                                    timeout=5)
                resp.raise_for_status()
                parsed = metrics_lib.parse_exposition(resp.text)
            except (requests.RequestException, ValueError) as e:
                rows.append((r['name'], rep['replica_id'], url, role,
                             num_hosts, f'scrape failed: {e}', '-',
                             '-', '-', '-', '-', '-', '-'))
                continue

            def total(name, parsed=parsed):
                return sum((parsed.get(name) or {}).values())

            busy = int(total('skytpu_engine_busy_slots'))
            slots = int(total('skytpu_engine_slots'))
            # Paged-KV replicas: pages used/total plus the prefix-
            # cache hit share; dense replicas show '-'.
            pages_total = int(total('skytpu_engine_kv_pages_total'))
            if pages_total:
                hits = total('skytpu_engine_prefix_cache_hits_total')
                misses = total(
                    'skytpu_engine_prefix_cache_misses_total')
                share = (f' {hits / (hits + misses):.0%}hit'
                         if hits + misses else '')
                pages = (f'{int(total("skytpu_engine_kv_pages_used"))}'
                         f'/{pages_total}{share}')
            else:
                pages = '-'
            # Router view from the replica side: LB-routed requests
            # and the share whose prompt prefix hit a pinned replica
            # (the skytpu_engine_routed_total{role,affinity} counter).
            routed = parsed.get('skytpu_engine_routed_total') or {}
            routed_total = sum(routed.values())
            if routed_total:
                hits = sum(v for labels, v in routed.items()
                           if dict(labels).get('affinity') == 'hit')
                affinity = f'{hits / routed_total:.0%}hit'
            else:
                affinity = '-'
            rows.append((
                r['name'], rep['replica_id'], url, role, num_hosts,
                f'{total("skytpu_engine_decode_tokens_per_s"):g}',
                f'{busy}/{slots}',
                pages,
                affinity,
                int(total('skytpu_engine_queue_depth')),
                _rank_lag(parsed),
                f'{fmt_ms(_hist_quantile(parsed, "skytpu_engine_ttft_seconds", 0.5))}'
                f'/{fmt_ms(_hist_quantile(parsed, "skytpu_engine_ttft_seconds", 0.99))}',
                f'{fmt_ms(_hist_quantile(parsed, "skytpu_engine_itl_seconds", 0.5))}'
                f'/{fmt_ms(_hist_quantile(parsed, "skytpu_engine_itl_seconds", 0.99))}',
            ))
    if not rows:
        click.echo('No READY replicas to scrape.')
    else:
        click.echo('')
        _print_table(['SERVICE', 'REPLICA', 'URL', 'ROLE', 'HOSTS',
                      'TOK/S', 'SLOTS', 'KV PAGES', 'AFFINITY',
                      'QUEUE', 'RANK LAG', 'TTFT p50/p99',
                      'ITL p50/p99'], rows)
    _serve_lb_table(records)
    _serve_router_table(records)


@serve_group.command(name='down')
@click.argument('service_names', nargs=-1, required=True)
@click.option('--purge', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_down(service_names, purge, yes):
    """Stop service(s) and terminate replicas."""
    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel
    if not yes:
        click.confirm(f'Tear down {", ".join(service_names)}?',
                      default=True, abort=True)
    for name in service_names:
        serve.down(name, purge=purge)
        click.echo(f'Service {name} torn down.')


def _log_sources(record) -> List[Dict[str, Any]]:
    """Every structured-log endpoint of one service: each replica
    front's /logs, the LB's /lb/logs, the controller's
    /controller/logs."""
    from skypilot_tpu.serve import http_protocol  # pylint: disable=import-outside-toplevel
    targets, lb_url = _trace_targets(record)
    sources: List[Dict[str, Any]] = [
        {'kind': 'replica', 'url': t['url'],
         'path': http_protocol.LOGS,
         'replica_id': t['replica_id'], 'role': t['role']}
        for t in targets]
    if lb_url:
        sources.append({'kind': 'lb', 'url': lb_url,
                        'path': http_protocol.LB_LOGS})
    port = record.get('controller_port')
    if port:
        sources.append({'kind': 'controller',
                        'url': f'http://127.0.0.1:{port}',
                        'path': http_protocol.CONTROLLER_LOGS})
    return sources


def _merge_log_records(batches, seen=None) -> List[Dict[str, Any]]:
    """Merge per-endpoint record batches into one timestamp-ordered
    stream.  Dedup matters because in-process fleets (tests, single
    host) share one ring: every endpoint exports the same records."""
    seen = seen if seen is not None else set()
    out: List[Dict[str, Any]] = []
    for records in batches:
        for rec in records:
            key = (rec.get('seq'), rec.get('ts'), rec.get('logger'),
                   rec.get('msg'))
            if key in seen:
                continue
            seen.add(key)
            out.append(rec)
    out.sort(key=lambda r: (float(r.get('ts') or 0.0),
                            int(r.get('seq') or 0)))
    return out


def _log_record_matches(rec, replica_id, role) -> bool:
    """Client-side identity filter — per-record, not per-endpoint,
    because record identity is authoritative (a shared ring tags each
    record with the process that emitted it)."""
    if replica_id is not None and rec.get('replica_id') != replica_id:
        return False
    if role is not None and rec.get('role') != role:
        return False
    return True


def _fmt_log_record(rec) -> str:
    import datetime  # pylint: disable=import-outside-toplevel
    ts = float(rec.get('ts') or 0.0)
    stamp = datetime.datetime.fromtimestamp(ts).strftime(
        '%m-%d %H:%M:%S.%f')[:-3]
    proc = rec.get('process')
    if proc == 'lb':
        who = 'lb'
    elif proc not in (None, 'replica'):
        who = str(proc)
    else:
        rid = rec.get('replica_id')
        who = f'replica {rid}' if rid is not None else 'replica'
        if rec.get('role'):
            who += f' ({rec["role"]})'
    line = (f'{stamp} {str(rec.get("level") or "?")[:1]} [{who}] '
            f'{rec.get("logger", "?")}: {rec.get("msg", "")}')
    if rec.get('request_id'):
        line += f' (req {rec["request_id"]})'
    return line


@serve_group.command(name='logs')
@click.argument('service_name', required=False, default=None)
@click.option('--replica', '-R', 'replica_id', type=int, default=None,
              help='Only records emitted by this replica.')
@click.option('--role', default=None,
              help='Only records emitted by replicas of this role.')
@click.option('--follow', '-f', is_flag=True, default=False,
              help='Keep streaming new records (live fleet tail).')
@click.option('--level', '-l', default=None,
              help='Minimum level (DEBUG/INFO/WARNING/ERROR).')
@click.option('--grep', 'grep_pat', default=None,
              help='Only records whose message matches this pattern.')
@click.option('--request-id', 'request_id', default=None,
              help='Only records bound to this request id.')
@click.option('--target', default=None,
              type=click.Choice(['replica', 'controller']),
              help='Legacy raw file tail (pre-structured-ring path).')
def serve_logs(service_name, replica_id, role, follow, level,
               grep_pat, request_id, target):
    """Stream the fleet's structured logs, merged by timestamp.

    Fans in every process's bounded log ring — each replica front's
    `GET /logs`, the LB's `/lb/logs`, the controller's
    `/controller/logs` — and merges the records into one
    identity-prefixed stream, so one request's prefill, KV handoff and
    decode lines from three different processes read as one story.
    Server-side filters (--level/--grep/--request-id) keep the fan-in
    cheap; --follow pages each source by its sequence cursor."""
    import time as time_lib  # pylint: disable=import-outside-toplevel

    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.observability import traces as traces_lib  # pylint: disable=import-outside-toplevel
    if target is not None:
        if service_name is None:
            raise click.ClickException('--target needs a service name.')
        serve.tail_logs(service_name, target=target,
                        replica_id=replica_id)
        return
    record = _pick_service(
        serve.status([service_name] if service_name else None),
        service_name)
    sources = _log_sources(record)
    if not sources:
        raise click.ClickException(
            f'Service {record["name"]} has no reachable processes.')
    cursors = {i: 0.0 for i in range(len(sources))}
    seen: set = set()

    def _poll() -> List[Dict[str, Any]]:
        batches = []
        for i, src in enumerate(sources):
            records = traces_lib.fetch_log_records(
                src['url'], src['path'], since=cursors[i] or None,
                level=level, grep=grep_pat, request_id=request_id)
            for rec in records:
                cursors[i] = max(cursors[i],
                                 float(rec.get('seq') or 0))
            batches.append(records)
        return [rec for rec in _merge_log_records(batches, seen)
                if _log_record_matches(rec, replica_id, role)]

    for rec in _poll():
        click.echo(_fmt_log_record(rec))
    if not follow:
        return
    try:
        while True:
            time_lib.sleep(1.0)
            for rec in _poll():
                click.echo(_fmt_log_record(rec))
    except KeyboardInterrupt:
        pass


def _trace_targets(record) -> Tuple[List[Dict[str, Any]],
                                    Optional[str]]:
    """(replica span targets, lb url) for one service record — every
    replica with a URL is queried (a DRAINING replica may still hold
    the span the user is after)."""
    targets = [{'url': rep['url'], 'replica_id': rep['replica_id'],
                'role': rep.get('role') or 'mixed'}
               for rep in record['replicas']
               if rep.get('url') and rep['status'] in
               ('READY', 'NOT_READY', 'DRAINING')]
    lb_port = record.get('load_balancer_port')
    lb_url = f'http://127.0.0.1:{lb_port}' if lb_port else None
    return targets, lb_url


def _pick_service(records, service_name: Optional[str]):
    if not records:
        raise click.ClickException('No services.')
    if service_name is None:
        if len(records) > 1:
            names = ', '.join(r['name'] for r in records)
            raise click.ClickException(
                f'Several services exist ({names}); pass --service.')
        return records[0]
    for record in records:
        if record['name'] == service_name:
            return record
    raise click.ClickException(f'Service {service_name!r} not found.')


@serve_group.command(name='trace')
@click.argument('request_id')
@click.option('--service', '-s', 'service_name', default=None,
              help='Service to query (default: the only one).')
@click.option('--export-trace', 'export_trace', default=None,
              help='Also write the stitched trace as a Chrome-trace '
                   'JSON to this path.')
def serve_trace(request_id, service_name, export_trace):
    """Stitch one request's spans across the fleet into a waterfall.

    Every process that touched the request exports its span segments
    (the LB's route/handoff/attempt phases via /lb/spans, each
    replica's engine + handoff-endpoint spans via /spans); this
    assembles them by request id into one end-to-end view — LB queue,
    route, KV handoff export/import, prefill, decode — with a failed
    attempt and its retry shown as distinct segments."""
    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.observability import traces as traces_lib  # pylint: disable=import-outside-toplevel
    record = _pick_service(
        serve.status([service_name] if service_name else None),
        service_name)
    targets, lb_url = _trace_targets(record)
    if not targets and not lb_url:
        raise click.ClickException(
            f'Service {record["name"]} has no reachable processes.')
    segments = traces_lib.collect(request_id, targets, lb_url)
    if not segments:
        raise click.ClickException(
            f'No spans found for request {request_id!r} (finished '
            'long ago and aged out of the bounded span stores, or '
            'never reached this service).')
    # The request's log lines, interleaved into the waterfall by wall
    # time (same fan-in as `serve logs --request-id`).
    log_records = _merge_log_records([
        traces_lib.fetch_log_records(src['url'], src['path'],
                                     request_id=request_id)
        for src in _log_sources(record)])
    click.echo(f'Trace {request_id} — {len(segments)} segment(s) '
               f'across {len({(s.get("process"), s.get("replica_id")) for s in segments})} '
               f'process(es):')
    for line in traces_lib.interleave_logs(segments, log_records):
        click.echo(f'  {line}')
    if export_trace:
        traces_lib.export_chrome_trace(segments, export_trace)
        click.echo(f'Chrome trace written to {export_trace} '
                   '(open in chrome://tracing or Perfetto).')


@serve_group.command(name='profile')
@click.argument('service_name', required=False, default=None)
@click.option('--replica', '-R', 'replica_id', type=int, default=None,
              help='Only this replica (default: every reachable one).')
@click.option('--export-trace', 'export_trace', default=None,
              help='Write the tick-phase ring as Chrome-trace JSON '
                   'to this path (chrome://tracing / Perfetto).')
def serve_profile(service_name, replica_id, export_trace):
    """Tick-phase profile of a service's replicas.

    Pulls each replica's `GET /profile` payload — the engine's bounded
    ring of per-tick phase timings (admit / prefill-chunk / decode-step
    / spec-verify / sample / page-scatter / handoff / slice-sync), the
    recompile sentinel's per-jit-entry compile counts, and
    device-memory watermarks — and renders per-phase quantiles plus a
    collapsed-stack summary (pipe into a flamegraph tool)."""
    import json  # pylint: disable=import-outside-toplevel

    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.observability import profiling  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import http_protocol  # pylint: disable=import-outside-toplevel
    record = _pick_service(
        serve.status([service_name] if service_name else None),
        service_name)
    targets, _ = _trace_targets(record)
    if replica_id is not None:
        targets = [t for t in targets
                   if t['replica_id'] == replica_id]
    if not targets:
        raise click.ClickException(
            f'Service {record["name"]} has no reachable replica'
            + (f' {replica_id}.' if replica_id is not None else 's.'))
    profiles = []
    for target in targets:
        try:
            resp = requests.get(
                target['url'].rstrip('/') + http_protocol.PROFILE,
                timeout=5)
            resp.raise_for_status()
            payload = resp.json()
        except (requests.RequestException, ValueError) as e:
            click.echo(f'replica {target["replica_id"]}: '
                       f'unreachable ({e})')
            continue
        if payload.get('profile'):
            profiles.append((target, payload['profile']))
    if not profiles:
        raise click.ClickException('No replica answered /profile with '
                                   'a profiling snapshot.')
    trace_events = []
    for target, snap in profiles:
        rid = target['replica_id']
        click.echo(f'Replica {rid} ({target.get("role") or "mixed"}) — '
                   f'{snap.get("ticks", 0)} profiled tick(s), ring '
                   f'{snap.get("ring_ticks")}:')
        rows = []
        for phase, agg in sorted((snap.get('phases') or {}).items()):
            def ms(v):
                return '-' if v is None else f'{v * 1e3:.3f}ms'
            rows.append((phase, agg.get('count', 0),
                         ms(agg.get('p50_s')), ms(agg.get('p99_s')),
                         ms(agg.get('max_s')),
                         f"{agg.get('total_s', 0.0) * 1e3:.1f}ms"))
        if rows:
            _print_table(['PHASE', 'COUNT', 'p50', 'p99', 'MAX',
                          'TOTAL'], rows)
        recomp = (snap.get('recompiles') or {})
        total_recompiles = recomp.get('steady_recompiles_total', 0)
        click.echo(f'  steady-state recompiles: {total_recompiles}')
        for fn, st in sorted((recomp.get('fns') or {}).items()):
            if st.get('steady_recompiles'):
                click.echo(f'    {fn}: {st["steady_recompiles"]} '
                           f'(compiles {st["compiles"]}, calls '
                           f'{st["calls"]})')
        mem = (snap.get('device_memory') or {}).get('watermark_bytes')
        if mem is not None:
            click.echo(f'  device memory watermark: {mem / 1e6:.1f} MB')
        click.echo('  collapsed stacks:')
        for line in profiling.collapsed_stacks(snap).splitlines():
            click.echo(f'    {line}')
        trace = profiling.chrome_trace(snap, pid=int(rid))
        trace_events.extend(trace['traceEvents'])
        click.echo('')
    if export_trace:
        with open(export_trace, 'w', encoding='utf-8') as f:
            json.dump({'traceEvents': trace_events,
                       'displayTimeUnit': 'ms'}, f)
        click.echo(f'Chrome trace written to {export_trace} '
                   '(open in chrome://tracing or Perfetto).')


def _sparkline(values, empty: str = '-') -> str:
    """Unicode sparkline of a binned series (None bins render as a
    space); scaled to the series max."""
    blocks = '▁▂▃▄▅▆▇█'
    present = [v for v in values or [] if v is not None]
    if not present:
        return empty
    hi = max(present)
    out = []
    for v in values:
        if v is None:
            out.append(' ')
        elif hi <= 0:
            out.append(blocks[0])
        else:
            out.append(blocks[min(len(blocks) - 1,
                                  int(v / hi * (len(blocks) - 1)
                                      + 0.5))])
    return ''.join(out)


def _fetch_telemetry(record) -> Optional[Dict[str, Any]]:
    """GET /controller/telemetry for one service (None when the
    controller is unreachable — `serve top` then shows fleet state
    only)."""
    import requests  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.serve import http_protocol  # pylint: disable=import-outside-toplevel
    port = record.get('controller_port')
    if not port:
        return None
    try:
        resp = requests.get(
            f'http://127.0.0.1:{port}'
            f'{http_protocol.CONTROLLER_TELEMETRY}',
            timeout=5)
        resp.raise_for_status()
        return resp.json()
    except (requests.RequestException, ValueError):
        return None


def _fmt_tick_breakdown(phases: Optional[Dict[str, float]],
                        top: int = 2) -> str:
    """Compact `phase NN%` summary of a replica's tick-phase rates
    (the dominant `top` phases, shares of the recorded total)."""
    if not phases:
        return '-'
    total = sum(v for v in phases.values() if v) or 0.0
    if total <= 0:
        return '-'
    ranked = sorted(phases.items(), key=lambda kv: -(kv[1] or 0.0))
    return ' '.join(f'{name} {100.0 * (v or 0.0) / total:.0f}%'
                    for name, v in ranked[:top])


def _render_top(records, telemetry_by_service) -> None:
    """One `serve top` frame from already-fetched data (pure render —
    tests drive this directly)."""
    for r in records:
        telemetry = telemetry_by_service.get(r['name']) or {}
        mfu = telemetry.get('mfu') or {}
        breakdown = telemetry.get('tick_breakdown') or {}
        recompiles = telemetry.get('recompiles') or {}
        err_rates = telemetry.get('log_error_rates') or {}
        ready = sum(1 for rep in r['replicas']
                    if rep['status'] == 'READY')
        click.echo(f"{r['name']}  [{r['status']}]  v{r['version']}  "
                   f"{ready}/{len(r['replicas'])} ready  "
                   f"LB :{r.get('load_balancer_port') or '-'}")
        def fmt_mfu(v):
            if v is None:
                return '-'
            # Tiny models / emulated chips produce real-but-minuscule
            # MFU; scientific notation beats rendering 0.0000.
            return f'{v:.4f}' if v >= 5e-4 or v == 0 else f'{v:.1e}'

        rows = []
        for rep in r['replicas']:
            rid = str(rep['replica_id'])
            recomp = recompiles.get(rid)
            err = err_rates.get(rid)
            rows.append((rep['replica_id'],
                         rep.get('role') or 'mixed',
                         rep['status'], rep.get('url') or '-',
                         fmt_mfu(mfu.get(rid)),
                         _fmt_tick_breakdown(breakdown.get(rid)),
                         '-' if recomp is None else f'{recomp:g}',
                         '-' if err is None else f'{err:.3g}'))
        if rows:
            _print_table(['REPLICA', 'ROLE', 'STATUS', 'URL', 'MFU',
                          'TICK-BREAKDOWN', 'RECOMPILES', 'ERR/s'],
                         rows)
        roles = telemetry.get('roles') or {}
        if roles:
            click.echo('')
            rows = []
            for role, sig in sorted(roles.items()):
                def fmt(v, suffix=''):
                    return '-' if v is None else f'{v:g}{suffix}'
                rows.append((
                    role, fmt(sig.get('qps')),
                    _sparkline(sig.get('qps_spark')),
                    _sparkline(sig.get('tokens_per_s_spark')),
                    fmt(sig.get('ttft_p99_ms'), 'ms'),
                    fmt(sig.get('itl_p99_ms'), 'ms')))
            _print_table(['ROLE', 'QPS', 'QPS HISTORY',
                          'TOK/S HISTORY', 'TTFT p99', 'ITL p99'],
                         rows)
        batch = telemetry.get('batch') or None
        if batch:
            # Bulk-inference plane: only rendered while a batch driver
            # is actually pushing rows through the fleet.
            click.echo('')
            epochs = batch.get('weight_epochs') or {}
            epoch_str = ','.join(
                f'{rid}:{ep}' for rid, ep in sorted(epochs.items())
                if rid is not None) or '-'
            rps = batch.get('rows_per_s')
            _print_table(
                ['BATCH ROWS', 'ROWS/s', 'WEIGHT EPOCHS', 'SWAPS'],
                [(f"{batch.get('rows_total', 0):g}",
                  '-' if rps is None else f'{rps:.3g}',
                  epoch_str,
                  f"{batch.get('weight_swaps_total', 0):g}")])
        slos = telemetry.get('slos') or []
        if slos:
            click.echo('')
            rows = [(s['slo'], s.get('target', '-'),
                     f"{s.get('burn_fast', 0):g}",
                     f"{s.get('burn_slow', 0):g}",
                     'BREACH' if s.get('breaching') else 'ok')
                    for s in slos]
            _print_table(['SLO', 'TARGET', 'BURN fast', 'BURN slow',
                          'STATUS'], rows)
        spikes = telemetry.get('log_spikes') or []
        if spikes:
            click.echo('')
            rows = [(s.get('replica_id', '?'),
                     f"{s.get('rate_fast', 0):g}",
                     f"{s.get('rate_slow', 0):g}",
                     f"{s.get('threshold', 0):g}",
                     'SPIKE' if s.get('spiking') else 'ok')
                    for s in spikes]
            _print_table(['LOG ERRORS', 'ERR/s fast', 'ERR/s slow',
                          'THRESHOLD', 'STATUS'], rows)
        slow = telemetry.get('slow_traces') or []
        if slow:
            click.echo('')
            rows = [(s.get('request_id', '?'),
                     s.get('replica_id', '-'),
                     s.get('role') or '-',
                     f"{s.get('duration_ms', 0):.1f}ms",
                     f"{s['ttft_ms']:.1f}ms"
                     if s.get('ttft_ms') is not None else '-',
                     s.get('status', '-'))
                    for s in slow[:8]]
            _print_table(['SLOWEST TRACES', 'REPLICA', 'ROLE',
                          'TOTAL', 'TTFT', 'STATUS'], rows)
        click.echo('')


@serve_group.command(name='top')
@click.argument('service_names', nargs=-1)
@click.option('--refresh', '-r', 'refresh_every', type=float,
              default=2.0, help='Redraw every N seconds.')
@click.option('--once', is_flag=True, default=False,
              help='Print one frame and exit (scripting/CI).')
def serve_top(service_names, refresh_every, once):
    """Live fleet dashboard: replica table with per-replica MFU,
    per-role QPS/throughput sparklines and latency quantiles from the
    controller's telemetry ring buffers, SLO burn status, and the
    slowest recent traces."""
    import time as time_lib  # pylint: disable=import-outside-toplevel

    from skypilot_tpu import serve  # pylint: disable=import-outside-toplevel

    def _frame():
        records = serve.status(list(service_names) or None)
        if not records:
            click.echo('No services.')
            return
        telemetry = {r['name']: _fetch_telemetry(r) for r in records}
        _render_top(records, telemetry)

    if once or refresh_every <= 0:
        _frame()
        return
    try:
        while True:
            click.clear()
            _frame()
            time_lib.sleep(refresh_every)
    except KeyboardInterrupt:
        pass


# ------------------------------------------------------------ bench group


@cli.group(name='bench')
def bench_group():
    """Benchmark a task across candidate resources ($/step)."""


@bench_group.command(name='launch')
@click.argument('entrypoint')
@click.option('--benchmark', '-b', required=True, help='Benchmark name.')
@click.option('--gpus', '--accelerators', 'candidate_accels',
              multiple=True, required=True,
              help="Candidate accelerators (repeatable), e.g. "
                   "-A tpu-v5e-8 -A A100:8.")
@click.option('--cloud', default=None)
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_launch(entrypoint, benchmark, candidate_accels, cloud, yes):
    """Launch ENTRYPOINT once per candidate accelerator."""
    from skypilot_tpu import benchmark as bench_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import resources as resources_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import task as task_lib  # pylint: disable=import-outside-toplevel
    task = task_lib.Task.from_yaml(entrypoint)
    candidates = [
        resources_lib.Resources(cloud=cloud, accelerators=accel)
        for accel in candidate_accels
    ]
    if not yes:
        click.confirm(
            f'Launch {len(candidates)} benchmark cluster(s)?',
            default=True, abort=True)
    clusters = bench_lib.launch_benchmark(task, benchmark, candidates)
    click.echo(f'Benchmark {benchmark} running on: {", ".join(clusters)}')


@bench_group.command(name='show')
@click.argument('benchmark')
def bench_show(benchmark):
    """Collect and show benchmark results."""
    from skypilot_tpu import benchmark as bench_lib  # pylint: disable=import-outside-toplevel
    results = bench_lib.get_benchmark_results(benchmark)
    rows = []
    for r in results:
        rows.append((r['cluster'], r['resources'] or '-',
                     r['num_steps'] or '-',
                     f"{r['seconds_per_step']:.3f}"
                     if r['seconds_per_step'] else '-',
                     f"{r['first_step_seconds']:.1f}"
                     if r['first_step_seconds'] else '-',
                     f"${r['cost_per_step']:.6f}"
                     if r['cost_per_step'] else '-'))
    _print_table(['CLUSTER', 'RESOURCES', 'STEPS', 'SEC/STEP',
                  'FIRST STEP (s)', '$/STEP'], rows)


@bench_group.command(name='ls')
def bench_ls():
    """List benchmarks."""
    from skypilot_tpu.benchmark import benchmark_state  # pylint: disable=import-outside-toplevel
    rows = [(b['name'],) for b in benchmark_state.get_benchmarks()]
    _print_table(['BENCHMARK'], rows)


@bench_group.command(name='down')
@click.argument('benchmark')
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_down(benchmark, yes):
    """Terminate all clusters of a benchmark."""
    from skypilot_tpu import benchmark as bench_lib  # pylint: disable=import-outside-toplevel
    if not yes:
        click.confirm(f'Tear down benchmark {benchmark} clusters?',
                      default=True, abort=True)
    bench_lib.down_benchmark_clusters(benchmark)
    click.echo('Done.')


@bench_group.command(name='delete')
@click.argument('benchmark')
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_delete(benchmark, yes):
    """Delete a benchmark's records."""
    from skypilot_tpu.benchmark import benchmark_state  # pylint: disable=import-outside-toplevel
    if not yes:
        click.confirm(f'Delete benchmark {benchmark}?', default=True,
                      abort=True)
    benchmark_state.remove_benchmark(benchmark)
    click.echo('Deleted.')


@bench_group.command(name='diff')
@click.option('--last', 'last_n', type=int, default=None,
              help='Baseline only the last N prior runs of each '
                   'group (default: all of them).')
@click.option('--history', 'history_file', default=None,
              help='History file (default: BENCH_history.jsonl at the '
                   'repo root, or SKYTPU_BENCH_HISTORY_PATH).')
@click.option('--min-rel', type=float,
              default=None, help='Minimum relative move that can '
              'count as a regression (default 0.10).')
def bench_diff(last_n, history_file, min_rel):
    """Diff the newest bench run of each (metric, config) group
    against its history with noise-aware thresholds.

    `bench.py` / `bench_serve.py` append one record per run to
    BENCH_history.jsonl; this compares throughput, latency quantiles,
    and MFU against the baseline runs and **exits non-zero when any
    key moved past ``max(min_rel, 3 x cv)`` in the bad direction** —
    wire it after a bench run for a perf-regression gate."""
    from skypilot_tpu.observability import bench_history  # pylint: disable=import-outside-toplevel
    records = bench_history.load_records(history_file)
    if not records:
        raise click.ClickException(
            f'No bench history at '
            f'{bench_history.history_path(history_file)} — run '
            f'bench_serve.py / bench.py first.')
    kwargs = {}
    if min_rel is not None:
        kwargs['min_rel'] = min_rel
    findings = bench_history.diff_records(records, last=last_n,
                                          **kwargs)
    if not findings:
        click.echo(f'{len(records)} run(s), but no group has two '
                   'comparable runs yet — nothing to diff.')
        return
    for line in bench_history.format_findings(findings):
        click.echo(line)
    regressions = [f for f in findings if f['regression']]
    if regressions:
        raise SystemExit(
            f'{len(regressions)} perf regression(s) detected.')
    click.echo('No regressions.')


# ---------------------------------------------------------- storage group


@cli.group(name='storage')
def storage_group():
    """Bucket-backed storage objects."""


@storage_group.command(name='ls')
def storage_ls():
    """List storage objects."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    records = core.storage_ls()
    rows = [(r['name'], r['status'],
             ', '.join(r['handle'].get('store_types', []))
             if isinstance(r.get('handle'), dict) else '-')
            for r in records]
    _print_table(['NAME', 'STATUS', 'STORES'], rows)


@storage_group.command(name='delete')
@click.argument('names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def storage_delete(names, yes):
    """Delete storage objects (and their buckets)."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import exceptions  # pylint: disable=import-outside-toplevel
    if not yes:
        click.confirm(f'Delete storage {", ".join(names)}?',
                      default=True, abort=True)
    for name in names:
        try:
            core.storage_delete(name)
        except exceptions.StorageError as e:
            click.echo(str(e), err=True)
            continue
        click.echo(f'Storage {name} deleted.')


# ---------------------------------------------------------- catalog group


@cli.group(name='catalog')
def catalog_group():
    """Price catalogs (list/refresh)."""


@catalog_group.command(name='refresh')
@click.option('--cloud', default='gcp', help='Cloud whose catalog to fetch.')
@click.option('--api-key', default=None,
              help='API key for the billing catalog API (optional).')
def catalog_refresh(cloud, api_key):
    """Re-fetch price catalogs from the cloud's SKU API."""
    from skypilot_tpu import catalog  # pylint: disable=import-outside-toplevel
    try:
        out = catalog.refresh(cloud, api_key=api_key)
    except Exception as e:  # pylint: disable=broad-except
        raise click.ClickException(
            f'Catalog refresh failed ({e}); the previous catalog remains '
            'in use.')
    for name, path in out.items():
        click.echo(f'{name}: {path}')


@catalog_group.command(name='status')
@click.option('--cloud', default='gcp')
def catalog_status(cloud):
    """Show catalog freshness."""
    from skypilot_tpu import catalog  # pylint: disable=import-outside-toplevel
    rows = []
    for name, age in catalog.catalog_age_hours(cloud).items():
        rows.append((name, 'embedded snapshot' if age is None
                     else f'fetched {age:.1f}h ago'))
    _print_table(['CATALOG', 'FRESHNESS'], rows)


# ------------------------------------------------------------ chaos group


@cli.group(name='chaos')
def chaos_group():
    """Deterministic fault injection with journal-verified recovery.

    Scenarios drive real launch->fault->recover flows on the local
    backend and replay the flight-recorder journal through liveness/
    safety invariants.  See docs/chaos.md for the fault-plan DSL
    (SKYTPU_CHAOS_PLAN) and the injection-site vocabulary.
    """


@chaos_group.command(name='list')
@click.option('--sites', 'show_sites', is_flag=True, default=False,
              help='Also list the registered injection sites.')
def chaos_list(show_sites):
    """List chaos scenarios (and optionally the site vocabulary)."""
    from skypilot_tpu.chaos import faults as faults_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.chaos import scenarios as scenarios_lib  # pylint: disable=import-outside-toplevel
    rows = [(name, s.description)
            for name, s in sorted(scenarios_lib.SCENARIOS.items())]
    _print_table(['SCENARIO', 'DESCRIPTION'], rows)
    if show_sites:
        click.echo()
        _print_table(
            ['SITE', 'WHERE / EFFECT NOTES'],
            [(name, desc.replace('\n', ' '))
             for name, desc in sorted(faults_lib.SITES.items())])


@chaos_group.command(name='run')
@click.argument('scenario')
@click.option('--seed', type=int, default=0,
              help='Fault-plan seed; the same seed reproduces the '
                   'identical fault sequence.')
@click.option('--export-trace', 'export_trace', default=None,
              help='Write the scenario\'s merged journal as a '
                   'Chrome-trace JSON to this path.')
def chaos_run(scenario, seed, export_trace):
    """Run one chaos scenario and verify its journal invariants."""
    from skypilot_tpu.chaos import scenarios as scenarios_lib  # pylint: disable=import-outside-toplevel
    try:
        result = scenarios_lib.run_scenario(scenario, seed=seed,
                                            export_trace=export_trace)
    except ValueError as e:
        raise click.ClickException(str(e))
    click.echo(result.summary())
    if result.fault_sequence:
        click.echo('Fault sequence:')
        for fault in result.fault_sequence:
            click.echo(f'  #{fault["call"]:<3d} {fault["site"]:<24s} '
                       f'{fault["effect"]}')
    for key, value in sorted(result.details.items()):
        click.echo(f'  {key}: {value}')
    if export_trace:
        click.echo(f'Chrome trace written to {export_trace} '
                   '(open in chrome://tracing or Perfetto).')
    if not result.ok:
        for violation in result.violations:
            click.echo(f'  VIOLATION: {violation}')
        raise click.ClickException(
            f'{len(result.violations)} invariant violation(s).')


def _changed_package_files(pkg_root) -> Optional[set]:
    """Package-relative paths of files touched vs git HEAD (staged,
    unstaged, and untracked); None when git is unavailable — the
    caller then falls back to the full-tree report."""
    import pathlib  # pylint: disable=import-outside-toplevel
    import subprocess  # pylint: disable=import-outside-toplevel
    repo_root = pathlib.Path(pkg_root).parent
    try:
        out = subprocess.run(
            ['git', 'status', '--porcelain', '--untracked-files=all'],
            cwd=repo_root, capture_output=True, text=True, timeout=10,
            check=True).stdout
    except (OSError, subprocess.SubprocessError):
        return None
    changed = set()
    prefix = pathlib.Path(pkg_root).name + '/'
    for line in out.splitlines():
        # XY <path> (or `XY <old> -> <new>` for renames: take the new).
        path = line[3:].split(' -> ')[-1].strip().strip('"')
        if path.startswith(prefix):
            changed.add(path[len(prefix):])
    return changed


@cli.command()
@click.option('--rule', 'rules', multiple=True,
              help='Run only the passes owning these rule ids '
                   '(repeatable); framework rules always run.')
@click.option('--json', 'as_json', is_flag=True, default=False,
              help='Deterministic JSON report (diffable; byte-'
                   'identical across runs on one tree).')
@click.option('--list-rules', is_flag=True, default=False,
              help='Print the rule catalog and exit.')
@click.option('--changed', 'changed_only', is_flag=True, default=False,
              help='Report only findings in files changed vs git HEAD '
                   '(staged/unstaged/untracked).  The FULL package is '
                   'still indexed and every pass still runs — cross-'
                   'module contracts need the whole tree — only the '
                   'report is filtered, for fast fix iteration.')
@click.option('--update-baseline', is_flag=True, default=False,
              help='Grandfather every current unsuppressed finding '
                   'into lint-baseline.json (the file only shrinks '
                   'after that: stale entries fail lint).')
def lint(rules, as_json, list_rules, changed_only, update_baseline):
    """Static analysis over the whole package (AST-only, no imports).

    Exit 1 on unsuppressed findings.  Rule catalog, suppression
    syntax, and the baseline workflow: docs/static-analysis.md.
    """
    import pathlib  # pylint: disable=import-outside-toplevel

    from skypilot_tpu import analysis  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.analysis import core as lint_core  # pylint: disable=import-outside-toplevel
    if list_rules:
        for rule, owner in sorted(lint_core.rule_catalog().items()):
            click.echo(f'{rule:24s} {owner}')
        return
    if changed_only and update_baseline:
        raise click.ClickException(
            '--changed filters the report; the baseline must be '
            'written from a full run.')
    pkg_root = pathlib.Path(__file__).resolve().parent
    baseline = pkg_root.parent / lint_core.BASELINE_FILENAME
    idx = analysis.PackageIndex(pkg_root)
    try:
        result = lint_core.run_lint(
            idx, rules=list(rules) or None,
            baseline_path=baseline if baseline.is_file() else None)
    except ValueError as e:   # unknown --rule
        raise click.ClickException(str(e))
    if changed_only:
        changed = _changed_package_files(pkg_root)
        if changed is None:
            click.echo('git unavailable; reporting the full tree.',
                       err=True)
        else:
            result.findings = [f for f in result.findings
                               if f.file in changed]
            result.suppressed = [f for f in result.suppressed
                                 if f.file in changed]
            result.baselined = [f for f in result.baselined
                                if f.file in changed]
    if update_baseline:
        # Keep still-reproducing grandfathered findings, add the new
        # ones; never baseline the framework's own meta-findings.
        keep = [f for f in result.findings + result.baselined
                if f.rule not in (lint_core.RULE_BASELINE_STALE,
                                  'suppression-invalid')]
        lint_core.write_baseline(baseline, keep)
        click.echo(f'Baselined {len(keep)} finding(s) into '
                   f'{baseline}.')
        return
    if as_json:
        click.echo(result.to_json())
    else:
        for f in result.findings:
            click.echo(f'skypilot_tpu/{f.render()}')
        click.echo(f'{len(result.findings)} finding(s), '
                   f'{len(result.suppressed)} suppressed, '
                   f'{len(result.baselined)} baselined '
                   f'({len(idx.modules)} modules, '
                   f'{result.duration_s:.1f}s).')
    if not result.ok:
        raise SystemExit(1)


def main() -> None:
    # Pin the completion trigger var: click otherwise derives it from
    # the program name, which breaks completion when invoked as
    # `python -m skypilot_tpu.cli` instead of the `skytpu` script.
    cli(complete_var='_SKYTPU_COMPLETE')


if __name__ == '__main__':
    main()
