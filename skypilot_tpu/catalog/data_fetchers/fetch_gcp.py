"""GCP catalog fetcher: Cloud Billing SKU API -> price CSVs.

Parity: /root/reference/sky/clouds/service_catalog/data_fetchers/
fetch_gcp.py:34-50 (SKU scrape incl. TPU pricing).  Rebuilt with the
same injectable-transport seam as provision/gcp/tpu_api.py so the whole
pipeline is unit-testable without network, and with a component-pricing
model: an instance shape prices as cores*core_price + ram_gib*ram_price
+ gpus*gpu_price from the machine family's SKUs, which is how GCP
itself bills N2/A2/A3/G2.

Output: gcp_instances.csv + gcp_tpus.csv under $SKYTPU_HOME/catalogs/
plus a .meta.json freshness stamp consumed by catalog.common's TTL
check.
"""
from __future__ import annotations

import collections
import csv
import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)

BILLING_API = 'https://cloudbilling.googleapis.com/v1'
# Compute Engine's fixed service id in the billing catalog (public,
# stable; same constant the reference uses).
COMPUTE_SERVICE_ID = '6F81-5844-456A'

# Instance *shapes* are static facts (vCPU/mem/GPU count per type);
# only their prices move.  Component keys: (family, resource).
# GPU-attached families price as VM components + per-GPU SKU.
_SHAPES: Tuple[Dict[str, Any], ...] = (
    # family, instance_type, vcpus, mem, gpu (name, count)
    *({'family': 'N2', 'instance_type': f'n2-standard-{n}',
       'vcpus': n, 'memory': 4 * n, 'gpu': None}
      for n in (2, 4, 8, 16, 32, 64)),
    *({'family': 'A2', 'instance_type': f'a2-highgpu-{n}g',
       'vcpus': 12 * n, 'memory': 85 * n, 'gpu': ('A100', n)}
      for n in (1, 2, 4, 8)),
    *({'family': 'A2', 'instance_type': f'a2-ultragpu-{n}g',
       'vcpus': 12 * n, 'memory': 170 * n, 'gpu': ('A100-80GB', n)}
      for n in (1, 2, 4, 8)),
    {'family': 'A3', 'instance_type': 'a3-highgpu-8g', 'vcpus': 208,
     'memory': 1872, 'gpu': ('H100', 8)},
    {'family': 'A3', 'instance_type': 'a3-megagpu-8g', 'vcpus': 208,
     'memory': 1872, 'gpu': ('H100-MEGA', 8)},
    {'family': 'G2', 'instance_type': 'g2-standard-4', 'vcpus': 4,
     'memory': 16, 'gpu': ('L4', 1)},
    {'family': 'G2', 'instance_type': 'g2-standard-8', 'vcpus': 8,
     'memory': 32, 'gpu': ('L4', 1)},
    {'family': 'G2', 'instance_type': 'g2-standard-24', 'vcpus': 24,
     'memory': 96, 'gpu': ('L4', 2)},
    {'family': 'G2', 'instance_type': 'g2-standard-48', 'vcpus': 48,
     'memory': 192, 'gpu': ('L4', 4)},
    *({'family': 'N1', 'instance_type': f'n1-standard-8-t4x{n}',
       'vcpus': 8, 'memory': 30, 'gpu': ('T4', n)} for n in (1, 2, 4)),
    *({'family': 'N1', 'instance_type': f'n1-standard-8-v100x{n}',
       'vcpus': 8, 'memory': 30, 'gpu': ('V100', n)} for n in (1, 4, 8)),
)

# SKU description fragment -> GPU name (per-GPU-hour SKUs).
_GPU_SKU_PATTERNS: Tuple[Tuple[str, str], ...] = (
    ('nvidia tesla a100 80gb', 'A100-80GB'),
    ('nvidia a100 80gb', 'A100-80GB'),
    ('nvidia tesla a100', 'A100'),
    ('nvidia h100 80gb plus', 'H100-MEGA'),
    ('nvidia h100 mega', 'H100-MEGA'),
    ('nvidia h100 80gb', 'H100'),
    ('nvidia l4', 'L4'),
    ('nvidia tesla t4', 'T4'),
    ('nvidia tesla v100', 'V100'),
)

# SKU description fragment -> TPU generation (per-chip-hour SKUs).
_TPU_SKU_PATTERNS: Tuple[Tuple[str, str], ...] = (
    ('tpu v6e', 'tpu-v6e'), ('tpu-v6e', 'tpu-v6e'),
    ('tpu v5p', 'tpu-v5p'),
    ('tpu v5e', 'tpu-v5e'), ('tpu v5 lite', 'tpu-v5e'),
    ('tpu v4', 'tpu-v4'),
    ('tpu v3', 'tpu-v3'),
    ('tpu v2', 'tpu-v2'),
)

# Zones emitted per region (suffix list).  Static topology fact.
_REGION_ZONES = {
    'us-central1': ('a', 'b', 'c', 'f'),
    'us-central2': ('b',),
    'us-east1': ('b', 'c', 'd'),
    'us-east5': ('a', 'b'),
    'us-west1': ('a', 'b'),
    'us-west4': ('a', 'b'),
    'europe-west4': ('a', 'b'),
    'asia-east1': ('c',),
    'asia-northeast1': ('b',),
    'asia-southeast1': ('b',),
}

Transport = Callable[[str, Dict[str, Any]], Dict[str, Any]]


def _default_transport(url: str, params: Dict[str, Any]) -> Dict[str, Any]:
    import requests  # pylint: disable=import-outside-toplevel
    resp = requests.get(url, params=params, timeout=30)
    resp.raise_for_status()
    return resp.json()


def list_skus(transport: Optional[Transport] = None,
              api_key: Optional[str] = None) -> List[Dict[str, Any]]:
    """All Compute Engine SKUs (paginated)."""
    transport = transport or _default_transport
    url = f'{BILLING_API}/services/{COMPUTE_SERVICE_ID}/skus'
    skus: List[Dict[str, Any]] = []
    page_token = ''
    while True:
        params: Dict[str, Any] = {'pageSize': 500}
        if api_key:
            params['key'] = api_key
        if page_token:
            params['pageToken'] = page_token
        payload = transport(url, params)
        skus.extend(payload.get('skus', ()))
        page_token = payload.get('nextPageToken', '')
        if not page_token:
            return skus


def _sku_unit_price(sku: Dict[str, Any]) -> Optional[float]:
    """$/unit/hour from the SKU's tiered rate (first tier)."""
    try:
        pricing = sku['pricingInfo'][0]['pricingExpression']
        tier = pricing['tieredRates'][0]['unitPrice']
        return int(tier.get('units', 0)) + tier.get('nanos', 0) / 1e9
    except (KeyError, IndexError, TypeError):
        return None


def _classify(sku: Dict[str, Any]):
    """-> (kind, key, spot) or None.

    kind 'gpu': key = gpu name; 'tpu': key = tpu generation;
    'core'/'ram': key = machine family.
    """
    category = sku.get('category', {})
    if category.get('serviceDisplayName') not in (None, 'Compute Engine'):
        return None
    usage = category.get('usageType', '')
    if usage not in ('OnDemand', 'Preemptible'):
        return None
    spot = usage == 'Preemptible'
    desc = sku.get('description', '').lower()
    if 'custom' in desc or 'sole tenancy' in desc or 'commitment' in desc:
        return None
    resource_group = category.get('resourceGroup', '')
    if resource_group == 'GPU' or 'gpu' in desc:
        for pattern, name in _GPU_SKU_PATTERNS:
            if pattern in desc:
                return 'gpu', name, spot
        return None
    if resource_group == 'TPU' or 'tpu' in desc:
        for pattern, gen in _TPU_SKU_PATTERNS:
            if pattern in desc:
                return 'tpu', gen, spot
        return None
    for family in ('N2', 'A2', 'A3', 'G2', 'N1'):
        if desc.startswith(f'{family.lower()} instance'):
            if 'core' in desc:
                return 'core', family, spot
            if 'ram' in desc:
                return 'ram', family, spot
    return None


def _index_prices(skus: Iterable[Dict[str, Any]]):
    """-> {(kind, key, region, spot): $/unit/hr} (min across SKUs)."""
    prices: Dict[Tuple[str, str, str, bool], float] = {}
    for sku in skus:
        classified = _classify(sku)
        if classified is None:
            continue
        kind, key, spot = classified
        unit_price = _sku_unit_price(sku)
        if unit_price is None or unit_price <= 0:
            continue
        for region in sku.get('serviceRegions', ()):
            entry = (kind, key, region, spot)
            if entry not in prices or unit_price < prices[entry]:
                prices[entry] = unit_price
    return prices


def _shape_price(shape: Dict[str, Any], prices, region: str,
                 spot: bool) -> Optional[float]:
    family = shape['family']
    core = prices.get(('core', family, region, spot))
    ram = prices.get(('ram', family, region, spot))
    if core is None or ram is None:
        return None
    total = shape['vcpus'] * core + shape['memory'] * ram
    if shape['gpu'] is not None:
        name, count = shape['gpu']
        gpu = prices.get(('gpu', name, region, spot))
        if gpu is None:
            return None
        total += count * gpu
    return total


def build_instance_rows(prices) -> List[Dict[str, Any]]:
    rows = []
    for shape in _SHAPES:
        for region, zones in _REGION_ZONES.items():
            price = _shape_price(shape, prices, region, spot=False)
            spot_price = _shape_price(shape, prices, region, spot=True)
            if price is None:
                continue
            gpu_name, gpu_count = shape['gpu'] or (None, 0)
            for suffix in zones:
                rows.append({
                    'InstanceType': shape['instance_type'],
                    'AcceleratorName': gpu_name or '',
                    'AcceleratorCount': gpu_count,
                    'vCPUs': shape['vcpus'],
                    'MemoryGiB': shape['memory'],
                    'Price': round(price, 4),
                    # No preemptible SKU -> blank, never a synthesized
                    # price: the optimizer must not rank spot
                    # feasibility on made-up numbers (VERDICT r2 #6).
                    'SpotPrice': (round(spot_price, 4)
                                  if spot_price is not None else ''),
                    'Region': region,
                    'AvailabilityZone': f'{region}-{suffix}',
                })
    return rows


def build_tpu_rows(prices) -> List[Dict[str, Any]]:
    rows = []
    generations = sorted({k for (kind, k, _, _) in prices
                          if kind == 'tpu'})
    for gen in generations:
        regions = sorted({r for (kind, k, r, _) in prices
                          if kind == 'tpu' and k == gen})
        for region in regions:
            price = prices.get(('tpu', gen, region, False))
            if price is None:
                continue
            spot = prices.get(('tpu', gen, region, True))
            for suffix in _REGION_ZONES.get(region, ('a',)):
                rows.append({
                    'AcceleratorName': gen,
                    'PricePerChipHour': round(price, 4),
                    # Blank when no preemptible SKU exists (see
                    # build_instance_rows) — spot capacity simply is not
                    # offered there.
                    'SpotPricePerChipHour': (round(spot, 4)
                                             if spot is not None else ''),
                    'Region': region,
                    'AvailabilityZone': f'{region}-{suffix}',
                })
    return rows


def _write_csv(path: str, rows: List[Dict[str, Any]]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)


def fetch(transport: Optional[Transport] = None,
          api_key: Optional[str] = None,
          output_dir: Optional[str] = None) -> Dict[str, str]:
    """Fetch SKUs and (re)write the GCP catalogs.

    Returns {csv_name: path}.  Raises on network/API failure — callers
    keep serving the previous (or embedded) catalog.
    """
    skus = list_skus(transport, api_key)
    prices = _index_prices(skus)
    instance_rows = build_instance_rows(prices)
    tpu_rows = build_tpu_rows(prices)
    if not instance_rows or not tpu_rows:
        raise RuntimeError(
            f'GCP SKU parse produced {len(instance_rows)} instance rows / '
            f'{len(tpu_rows)} TPU rows; refusing to overwrite catalogs.')
    if output_dir is None:
        output_dir = os.path.join(common_utils.skytpu_home(), 'catalogs')
    out = {}
    for name, rows in (('gcp_instances.csv', instance_rows),
                       ('gcp_tpus.csv', tpu_rows)):
        path = os.path.join(output_dir, name)
        _write_csv(path, rows)
        with open(f'{path}.meta.json', 'w', encoding='utf-8') as f:
            json.dump({'fetched_at': time.time(), 'num_rows': len(rows)}, f)
        out[name] = path
    logger.info(f'GCP catalog refreshed: {len(instance_rows)} instance '
                f'rows, {len(tpu_rows)} TPU rows.')
    return out
