"""Catalog data fetchers: rebuild the price CSVs from cloud APIs.

Parity: /root/reference/sky/clouds/service_catalog/data_fetchers/
(fetch_gcp.py scrapes the GCP SKU API incl. TPU pricing, fetch_gcp.py:34-50).
"""
from skypilot_tpu.catalog.data_fetchers import fetch_gcp

FETCHERS = {
    'gcp': fetch_gcp.fetch,
}

__all__ = ['FETCHERS', 'fetch_gcp']
