"""Catalog data fetchers: rebuild the price CSVs from cloud APIs.

Parity: /root/reference/sky/clouds/service_catalog/data_fetchers/
(fetch_gcp.py scrapes the GCP SKU API incl. TPU pricing, fetch_gcp.py:34-50).
"""
from skypilot_tpu.catalog.data_fetchers import fetch_aws
from skypilot_tpu.catalog.data_fetchers import fetch_gcp

FETCHERS = {
    'aws': fetch_aws.fetch,
    'gcp': fetch_gcp.fetch,
}

__all__ = ['FETCHERS', 'fetch_aws', 'fetch_gcp']
