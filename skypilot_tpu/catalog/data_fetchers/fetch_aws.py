"""AWS catalog fetcher: public EC2 pricing bulk JSON -> price CSV.

Parity: /root/reference/sky/clouds/service_catalog/data_fetchers/
fetch_aws.py — rebuilt WITHOUT boto3: the no-auth pricing bulk feed
(https://pricing.us-east-1.amazonaws.com/offers/v1.0/aws/AmazonEC2/
current/<region>/index.json) provides on-demand prices per region, so
the whole pipeline needs only an injectable GET transport (same seam
as fetch_gcp.py).  Spot prices are NOT in the bulk feed and are
emitted blank — never synthesized (same honesty contract as the GCP
fetcher's preemptible SKUs).
"""
from __future__ import annotations

import csv
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)

PRICING_URL = ('https://pricing.us-east-1.amazonaws.com/offers/v1.0/'
               'aws/AmazonEC2/current/{region}/index.json')

# Instance families worth cataloging (GPU boxes + the m6i CPU family);
# everything else in the ~100MB feed is skipped during parse.
_FAMILIES = ('p3', 'p4d', 'p4de', 'p5', 'g4dn', 'g5', 'g6', 'm6i')

# instanceType prefix -> accelerator name (the feed's gpu field gives
# the count; the model must come from the family).
_GPU_BY_FAMILY = {
    'p3': 'V100', 'p4d': 'A100', 'p4de': 'A100-80GB', 'p5': 'H100',
    'g4dn': 'T4', 'g5': 'A10G', 'g6': 'L4',
}

DEFAULT_REGIONS = ('us-east-1', 'us-west-2', 'eu-west-1')
# The bulk feed keys AZs only indirectly; emit the standard suffixes
# (same static-topology simplification as fetch_gcp._REGION_ZONES).
_ZONE_SUFFIXES = ('a', 'b', 'c')

Transport = Callable[[str], Dict[str, Any]]


def _default_transport(url: str) -> Dict[str, Any]:
    import requests  # pylint: disable=import-outside-toplevel
    resp = requests.get(url, timeout=300)
    resp.raise_for_status()
    return resp.json()


def _family(instance_type: str) -> str:
    return instance_type.split('.', 1)[0]


def parse_region(payload: Dict[str, Any], region: str
                 ) -> List[Dict[str, Any]]:
    """One region's bulk feed -> catalog rows."""
    products = payload.get('products', {})
    terms = payload.get('terms', {}).get('OnDemand', {})

    def ondemand_price(sku: str) -> Optional[float]:
        for offer in terms.get(sku, {}).values():
            for dim in offer.get('priceDimensions', {}).values():
                usd = dim.get('pricePerUnit', {}).get('USD')
                if usd is not None:
                    try:
                        price = float(usd)
                    except ValueError:
                        continue
                    if price > 0:
                        return price
        return None

    rows = []
    for sku, product in products.items():
        attrs = product.get('attributes', {})
        itype = attrs.get('instanceType', '')
        if not itype or _family(itype) not in _FAMILIES:
            continue
        # Shared-tenancy Linux on-demand boxes only (the reference's
        # fetcher applies the same filters via the pricing API).
        if (attrs.get('operatingSystem') != 'Linux' or
                attrs.get('tenancy') not in ('Shared',) or
                attrs.get('preInstalledSw', 'NA') != 'NA' or
                attrs.get('capacitystatus') != 'Used'):
            continue
        price = ondemand_price(sku)
        if price is None:
            continue
        try:
            vcpus = int(attrs.get('vcpu', 0))
            memory = float(
                attrs.get('memory', '0').replace(' GiB', '').replace(
                    ',', ''))
            gpu_count = int(attrs.get('gpu', 0) or 0)
        except ValueError:
            continue
        gpu_name = _GPU_BY_FAMILY.get(_family(itype), '') \
            if gpu_count else ''
        for suffix in _ZONE_SUFFIXES:
            rows.append({
                'InstanceType': itype,
                'AcceleratorName': gpu_name,
                'AcceleratorCount': gpu_count,
                'vCPUs': vcpus,
                'MemoryGiB': memory,
                'Price': round(price, 4),
                # Spot is not in the bulk feed: blank, never made up.
                'SpotPrice': '',
                'Region': region,
                'AvailabilityZone': f'{region}{suffix}',
            })
    rows.sort(key=lambda r: (r['InstanceType'], r['AvailabilityZone']))
    return rows


def fetch(transport: Optional[Transport] = None,
          regions: Optional[List[str]] = None,
          output_dir: Optional[str] = None) -> Dict[str, str]:
    """Fetch the bulk pricing feeds and (re)write aws_instances.csv.

    Raises on network failure — callers keep serving the previous (or
    embedded) catalog, exactly like the GCP fetcher.
    """
    transport = transport or _default_transport
    regions = list(regions or DEFAULT_REGIONS)
    rows: List[Dict[str, Any]] = []
    for region in regions:
        payload = transport(PRICING_URL.format(region=region))
        rows.extend(parse_region(payload, region))
    if not rows:
        raise RuntimeError(
            'AWS pricing parse produced 0 rows; refusing to overwrite '
            'the catalog.')
    if output_dir is None:
        output_dir = os.path.join(common_utils.skytpu_home(), 'catalogs')
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, 'aws_instances.csv')
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)
    with open(f'{path}.meta.json', 'w', encoding='utf-8') as f:
        json.dump({'fetched_at': time.time(), 'num_rows': len(rows)}, f)
    logger.info(f'AWS catalog refreshed: {len(rows)} instance rows '
                f'across {len(regions)} region(s).')
    return {'aws_instances.csv': path}
