"""Catalog query API: accelerators, prices, instance shapes, regions.

Parity: /root/reference/sky/clouds/service_catalog/__init__.py:56-357
(list_accelerators, get_hourly_cost, get_instance_type_for_accelerator,
validate_region_zone, ...) — reorganized so TPUs price by (generation zone
offering × chip count) via `TpuSliceSpec` instead of an instance-type table.

Every function takes a `cloud` name string ('gcp', 'local'); the cloud
classes in `skypilot_tpu.clouds` call through here.
"""
from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.catalog import common
from skypilot_tpu.utils import accelerator_registry

InstanceTypeInfo = common.InstanceTypeInfo
TpuOffering = common.TpuOffering

_INSTANCE_CSVS = {
    'aws': 'aws_instances.csv',
    'azure': 'azure_instances.csv',
    'cudo': 'cudo_instances.csv',
    'fluidstack': 'fluidstack_instances.csv',
    'gcp': 'gcp_instances.csv',
    'ibm': 'ibm_instances.csv',
    'lambda': 'lambda_instances.csv',
    'local': 'local_instances.csv',
    'oci': 'oci_instances.csv',
    'paperspace': 'paperspace_instances.csv',
    'runpod': 'runpod_instances.csv',
}
_TPU_CSVS = {
    'gcp': 'gcp_tpus.csv',
}


def _instances(cloud: str) -> Tuple[InstanceTypeInfo, ...]:
    csv_name = _INSTANCE_CSVS.get(cloud)
    if csv_name is None:
        return ()
    return common.load_instance_catalog(cloud, csv_name)


def _tpus(cloud: str) -> Tuple[TpuOffering, ...]:
    csv_name = _TPU_CSVS.get(cloud)
    if csv_name is None:
        return ()
    return common.load_tpu_catalog(cloud, csv_name)


# ------------------------------------------------------------------ pricing


def get_tpu_hourly_cost(cloud: str,
                        accelerator_name: str,
                        use_spot: bool = False,
                        region: Optional[str] = None,
                        zone: Optional[str] = None) -> float:
    """Slice $/hr = chips × per-chip-hour price (host VMs included)."""
    spec = accelerator_registry.parse_tpu_name(accelerator_name)
    if spec is None:
        raise ValueError(f'Not a TPU accelerator: {accelerator_name}')
    offerings = [
        o for o in _tpus(cloud)
        if o.generation == spec.generation and
        (region is None or o.region == region) and
        (zone is None or o.zone == zone)
    ]
    if not offerings:
        raise exceptions.ResourcesUnavailableError(
            f'No {spec.generation} TPU offering in cloud={cloud} '
            f'region={region} zone={zone}.')
    if use_spot:
        # spot_price None = no preemptible SKU there; such offerings are
        # not spot-feasible (prices are never synthesized).
        spot_prices = [o.spot_price_per_chip_hour for o in offerings
                       if o.spot_price_per_chip_hour is not None]
        if not spot_prices:
            raise exceptions.ResourcesUnavailableError(
                f'No SPOT {spec.generation} TPU offering in cloud={cloud} '
                f'region={region} zone={zone} (no preemptible SKU).')
        per_chip = min(spot_prices)
    else:
        per_chip = min(o.price_per_chip_hour for o in offerings)
    return per_chip * spec.num_chips


def get_hourly_cost(cloud: str,
                    instance_type: str,
                    use_spot: bool = False,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    rows = [
        r for r in _instances(cloud)
        if r.instance_type == instance_type and
        (region is None or r.region == region) and
        (zone is None or r.zone == zone)
    ]
    if not rows:
        raise exceptions.ResourcesUnavailableError(
            f'Instance type {instance_type!r} not found in {cloud} catalog '
            f'(region={region}, zone={zone}).')
    if use_spot:
        spot_prices = [r.spot_price for r in rows
                       if r.spot_price is not None]
        if not spot_prices:
            raise exceptions.ResourcesUnavailableError(
                f'Instance type {instance_type!r} has no SPOT offering in '
                f'{cloud} (region={region}, zone={zone}).')
        return min(spot_prices)
    return min(r.price for r in rows)


# ----------------------------------------------------------------- lookups


def instance_type_exists(cloud: str, instance_type: str) -> bool:
    return any(r.instance_type == instance_type for r in _instances(cloud))


def get_vcpus_mem_from_instance_type(
        cloud: str, instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    for r in _instances(cloud):
        if r.instance_type == instance_type:
            return r.cpu_count, r.memory_gib
    return None, None


def get_accelerators_from_instance_type(
        cloud: str, instance_type: str) -> Optional[Dict[str, int]]:
    for r in _instances(cloud):
        if r.instance_type == instance_type:
            if r.accelerator_name is None:
                return None
            return {r.accelerator_name: r.accelerator_count}
    return None


def get_instance_type_for_accelerator(
        cloud: str,
        accelerator_name: str,
        accelerator_count: int,
        cpus: Optional[str] = None,
        memory: Optional[str] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None) -> Optional[List[str]]:
    """GPU accelerator → hosting instance types, cheapest first.

    TPU accelerators do not map to instance types (the slice is the unit);
    callers must branch on `accelerator_registry.is_tpu` first.
    """
    matches = [
        r for r in _instances(cloud)
        if r.accelerator_name is not None and
        r.accelerator_name.lower() == accelerator_name.lower() and
        r.accelerator_count == accelerator_count and
        (region is None or r.region == region) and
        (zone is None or r.zone == zone) and
        _fits(r, cpus, memory)
    ]
    if not matches:
        return None
    by_type: Dict[str, float] = {}
    for r in matches:
        by_type[r.instance_type] = min(r.price,
                                       by_type.get(r.instance_type, r.price))
    return sorted(by_type, key=by_type.get)


def _parse_cpus_or_memory(value: Optional[str]) -> Tuple[Optional[float], bool]:
    """'4' → (4, exact); '4+' → (4, at-least); None → (None, ...)."""
    if value is None:
        return None, False
    s = str(value).strip()
    if s.endswith('+'):
        return float(s[:-1]), True
    return float(s), False


def _fits(r: InstanceTypeInfo, cpus: Optional[str],
          memory: Optional[str]) -> bool:
    want_cpu, cpu_plus = _parse_cpus_or_memory(cpus)
    if want_cpu is not None:
        if cpu_plus and r.cpu_count < want_cpu:
            return False
        if not cpu_plus and r.cpu_count != want_cpu:
            return False
    want_mem, mem_plus = _parse_cpus_or_memory(memory)
    if want_mem is not None:
        if mem_plus and r.memory_gib < want_mem:
            return False
        if not mem_plus and r.memory_gib != want_mem:
            return False
    return True


def get_default_instance_type(cloud: str,
                              cpus: Optional[str] = None,
                              memory: Optional[str] = None) -> Optional[str]:
    """Cheapest CPU-only instance satisfying the cpus/memory request.

    Defaults mirror the reference (8 vCPUs, cpus-to-memory 1:4) when no
    request is given.
    """
    if cpus is None and memory is None:
        cpus = '8+'
    candidates = [
        r for r in _instances(cloud)
        if r.accelerator_name is None and _fits(r, cpus, memory)
    ]
    if not candidates:
        return None
    return min(candidates, key=lambda r: r.price).instance_type


# ----------------------------------------------------------- regions/zones


def get_region_zones_for_instance_type(
        cloud: str, instance_type: str,
        use_spot: bool = False) -> List[Tuple[str, str]]:
    rows = [r for r in _instances(cloud) if r.instance_type == instance_type]
    if use_spot:
        rows = [r for r in rows if r.spot_price is not None]
        rows.sort(key=lambda r: r.spot_price)
    else:
        rows.sort(key=lambda r: r.price)
    return [(r.region, r.zone) for r in rows]


def get_region_zones_for_tpu(cloud: str,
                             accelerator_name: str,
                             use_spot: bool = False) -> List[Tuple[str, str]]:
    spec = accelerator_registry.parse_tpu_name(accelerator_name)
    if spec is None:
        return []
    offs = [o for o in _tpus(cloud) if o.generation == spec.generation]
    if use_spot:
        offs = [o for o in offs if o.spot_price_per_chip_hour is not None]
        offs.sort(key=lambda o: o.spot_price_per_chip_hour)
    else:
        offs.sort(key=lambda o: o.price_per_chip_hour)
    return [(o.region, o.zone) for o in offs]


def validate_region_zone(
        cloud: str, region: Optional[str],
        zone: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """Check (region, zone) appear in the catalog; infer region from zone."""
    known: Dict[str, set] = collections.defaultdict(set)
    for r in _instances(cloud):
        known[r.region].add(r.zone)
    for o in _tpus(cloud):
        known[o.region].add(o.zone)
    if zone is not None and region is None:
        for reg, zones in known.items():
            if zone in zones:
                region = reg
                break
        else:
            raise ValueError(f'Unknown zone {zone!r} for cloud {cloud}.')
    if region is not None:
        if region not in known:
            raise ValueError(f'Unknown region {region!r} for cloud {cloud}. '
                             f'Known: {sorted(known)}')
        if zone is not None and zone not in known[region]:
            raise ValueError(f'Zone {zone!r} is not in region {region!r} '
                             f'for cloud {cloud}.')
    return region, zone


# ------------------------------------------------------------- enumeration


@dataclasses.dataclass(frozen=True)
class AcceleratorOffering:
    """One row of `list_accelerators` output (CLI `show-tpus` / `show-gpus`)."""
    cloud: str
    accelerator_name: str
    accelerator_count: int
    instance_type: Optional[str]   # None for TPU slices
    num_hosts: int
    price: float
    spot_price: Optional[float]    # None = no spot offering
    region: str


def list_accelerators(
        name_filter: Optional[str] = None,
        clouds: Optional[List[str]] = None,
        max_tpu_chips: int = 1024
) -> Dict[str, List[AcceleratorOffering]]:
    clouds = clouds or list(_INSTANCE_CSVS)
    result: Dict[str, List[AcceleratorOffering]] = collections.defaultdict(list)
    for cloud in clouds:
        seen_gpu = set()
        for r in _instances(cloud):
            if r.accelerator_name is None:
                continue
            key = (r.instance_type, r.region)
            if key in seen_gpu:
                continue
            seen_gpu.add(key)
            result[r.accelerator_name].append(
                AcceleratorOffering(cloud, r.accelerator_name,
                                    r.accelerator_count, r.instance_type, 1,
                                    r.price, r.spot_price, r.region))
        tpu_regions: Dict[str, TpuOffering] = {}
        for o in _tpus(cloud):
            cur = tpu_regions.get(o.generation)
            if cur is None or o.price_per_chip_hour < cur.price_per_chip_hour:
                tpu_regions[o.generation] = o
        for name in accelerator_registry.list_tpu_names(max_tpu_chips):
            spec = accelerator_registry.parse_tpu_name(name)
            assert spec is not None, name
            o = tpu_regions.get(spec.generation)
            if o is None:
                continue
            result[name].append(
                AcceleratorOffering(
                    cloud, name, spec.num_chips, None, spec.num_hosts,
                    o.price_per_chip_hour * spec.num_chips,
                    (o.spot_price_per_chip_hour * spec.num_chips
                     if o.spot_price_per_chip_hour is not None else None),
                    o.region))
    if name_filter:
        lowered = name_filter.lower()
        result = collections.defaultdict(
            list,
            {k: v for k, v in result.items() if lowered in k.lower()})
    return dict(result)


# ------------------------------------------------------------------ refresh


def refresh(cloud: str = 'gcp', **kwargs) -> Dict[str, str]:
    """Re-fetch the cloud's price catalogs into $SKYTPU_HOME/catalogs/.

    Parity: the reference's TTL auto-download
    (/root/reference/sky/clouds/service_catalog/common.py:122-234) made
    explicit; kwargs (e.g. `transport`, `api_key`) pass through to the
    fetcher.  Clears in-process caches so new prices apply immediately.
    """
    from skypilot_tpu.catalog import data_fetchers  # pylint: disable=import-outside-toplevel
    fetcher = data_fetchers.FETCHERS.get(cloud)
    if fetcher is None:
        raise ValueError(
            f'No catalog fetcher for cloud {cloud!r}; '
            f'have {sorted(data_fetchers.FETCHERS)}')
    out = fetcher(**kwargs)
    common.clear_catalog_caches()
    return out


def catalog_age_hours(cloud: str = 'gcp') -> Dict[str, Optional[float]]:
    """Freshness per catalog CSV (None = embedded snapshot in use)."""
    names = [n for n in (_INSTANCE_CSVS.get(cloud), _TPU_CSVS.get(cloud))
             if n is not None]
    return {name: common.catalog_age_hours(name) for name in names}
