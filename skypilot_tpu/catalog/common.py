"""Catalog data model + CSV loading with freshness (TTL) tracking.

Parity: /root/reference/sky/clouds/service_catalog/common.py:33-553
(`InstanceTypeInfo`, TTL-downloaded LazyDataFrame CSV catalogs, query
helpers — common.py:122-234). Differences: (1) plain-stdlib csv instead
of pandas — catalogs here are small embedded snapshots, refreshable by
`catalog.data_fetchers`; (2) TPU offerings are a separate first-class
table keyed by *generation* with per-chip-hour pricing, so every valid
slice shape (`tpu-v5p-64`) prices as chips × chip-price without a
combinatorial instance table; (3) refresh is explicit (`sky catalog
refresh` / catalog.refresh()) rather than an implicit download on
import — this image has no egress, and implicit network-on-import is
the reference behavior we deliberately dropped.  A fetched catalog
older than the TTL logs a staleness warning and keeps serving.
"""
from __future__ import annotations

import csv
import dataclasses
import functools
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_DATA_DIR = os.path.join(os.path.dirname(__file__), 'data')
# Reference pulls catalogs every 7 hours (common.py _PULL_FREQUENCY_HOURS);
# explicit-refresh model tolerates a longer default.
CATALOG_TTL_HOURS = 7 * 24
_warned_stale: set = set()


@dataclasses.dataclass(frozen=True)
class InstanceTypeInfo:
    """One (instance type, zone) VM offering."""
    cloud: str
    instance_type: str
    accelerator_name: Optional[str]
    accelerator_count: int
    cpu_count: float
    memory_gib: float
    price: float
    # None = no preemptible offering in this zone (never synthesized —
    # the optimizer must not rank on made-up spot prices).
    spot_price: Optional[float]
    region: str
    zone: str


@dataclasses.dataclass(frozen=True)
class TpuOffering:
    """One (TPU generation, zone) offering, priced per chip-hour.

    TPU-VM pricing includes the host VMs, so slice cost is simply
    num_chips * price_per_chip_hour.
    """
    cloud: str
    generation: str            # 'v5e'
    price_per_chip_hour: float
    spot_price_per_chip_hour: Optional[float]   # None = no spot offering
    region: str
    zone: str


def catalog_age_hours(name: str) -> Optional[float]:
    """Hours since the user catalog was fetched; None if only the
    embedded snapshot exists."""
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    meta = os.path.join(common_utils.skytpu_home(), 'catalogs',
                        f'{name}.meta.json')
    try:
        with open(meta, encoding='utf-8') as f:
            fetched_at = json.load(f)['fetched_at']
    except (OSError, ValueError, KeyError):
        return None
    return (time.time() - fetched_at) / 3600.0


def _read_csv(name: str) -> List[Dict[str, str]]:
    path = os.path.join(_DATA_DIR, name)
    # A user-refreshed catalog (written by data_fetchers) takes precedence.
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    user_path = os.path.join(common_utils.skytpu_home(), 'catalogs', name)
    if os.path.exists(user_path):
        path = user_path
        age = catalog_age_hours(name)
        if (age is not None and age > CATALOG_TTL_HOURS and
                name not in _warned_stale):
            _warned_stale.add(name)
            logger.warning(
                f'Catalog {name} is {age / 24:.1f} days old (TTL '
                f'{CATALOG_TTL_HOURS / 24:.0f}d); prices may be stale. '
                "Run 'sky catalog refresh' to update.")
    if not os.path.exists(path):
        return []
    with open(path, newline='', encoding='utf-8') as f:
        return list(csv.DictReader(f))


@functools.lru_cache(maxsize=None)
def load_instance_catalog(cloud: str, csv_name: str) -> Tuple[InstanceTypeInfo, ...]:
    rows = []
    for r in _read_csv(csv_name):
        rows.append(
            InstanceTypeInfo(
                cloud=cloud,
                instance_type=r['InstanceType'],
                accelerator_name=r['AcceleratorName'] or None,
                accelerator_count=int(r['AcceleratorCount'] or 0),
                cpu_count=float(r['vCPUs']),
                memory_gib=float(r['MemoryGiB']),
                price=float(r['Price']),
                spot_price=(float(r['SpotPrice'])
                            if r.get('SpotPrice') else None),
                region=r['Region'],
                zone=r['AvailabilityZone'],
            ))
    return tuple(rows)


@functools.lru_cache(maxsize=None)
def load_tpu_catalog(cloud: str, csv_name: str) -> Tuple[TpuOffering, ...]:
    rows = []
    for r in _read_csv(csv_name):
        # 'tpu-v5e' → 'v5e'
        generation = r['AcceleratorName'].removeprefix('tpu-')
        rows.append(
            TpuOffering(
                cloud=cloud,
                generation=generation,
                price_per_chip_hour=float(r['PricePerChipHour']),
                spot_price_per_chip_hour=(
                    float(r['SpotPricePerChipHour'])
                    if r.get('SpotPricePerChipHour') else None),
                region=r['Region'],
                zone=r['AvailabilityZone'],
            ))
    return tuple(rows)


def clear_catalog_caches() -> None:
    load_instance_catalog.cache_clear()
    load_tpu_catalog.cache_clear()
