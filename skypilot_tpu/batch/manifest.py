"""Sharded JSONL manifests + the exactly-once shard ledger.

A batch-infer run is rooted in one directory:

    manifest.json        num_shards / per-shard row counts / source
    shard-00000.jsonl    input rows (contiguous split of the source)
    ...
    ledger.jsonl         append-only progress log (rows + shard ends)
    output-00000.jsonl   one output row per input row, {shard, row_idx,
    ...                  tokens/completion, weight_version, ...}

Exactly-once protocol (the whole point of the ledger):

- ``commit_row`` appends the OUTPUT row first, then the ledger record.
  The `batch.shard_write` chaos site sits between the two appends — a
  driver dying there leaves an output row with no ledger record.
- Resume replays ``ledger.jsonl`` into a done-set and skips every
  ``(shard, row_idx)`` it names: no committed row ever re-runs (no
  duplicated work), no uncommitted row is skipped (no lost rows).
- A row that died mid-commit re-runs, so its output file can hold the
  row TWICE; ``finalize()`` rewrites each output shard keeping the
  first copy per ``(shard, row_idx)`` — exactly-once on rewrite.

Ledger appends are flushed + fsync'd: a record the driver acted on
(skipping the row after restart) must actually be on disk.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

MANIFEST_FILE = 'manifest.json'
LEDGER_FILE = 'ledger.jsonl'


def _shard_file(shard: int) -> str:
    return f'shard-{shard:05d}.jsonl'


def _output_file(shard: int) -> str:
    return f'output-{shard:05d}.jsonl'


def _maybe_journal(event: str, **fields) -> None:
    """Journal the batch lifecycle only while someone is watching (the
    `batch.shard_write` chaos site armed, or SKYTPU_BATCH_EVENTS set):
    the batch_exactly_once invariant replays these."""
    from skypilot_tpu.chaos import injector as chaos_injector  # pylint: disable=import-outside-toplevel
    if not (os.environ.get('SKYTPU_BATCH_EVENTS') or
            chaos_injector.site_armed('batch.shard_write')):
        return
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    try:
        events_lib.get_journal(
            os.path.join(events_lib.journal_root(),
                         'serve.jsonl')).append(event, **fields)
    except Exception:  # pylint: disable=broad-except
        pass  # recording must never break the driver


def build_manifest(input_path: str, out_dir: str, *,
                   num_shards: int = 8) -> 'Manifest':
    """Shard a source JSONL (one request object per line — `prompt`
    string or `prompt_ids` list, plus optional per-row overrides) into
    `out_dir` as a batch-infer manifest.  Rows split contiguously so a
    shard is a readable slice of the source."""
    if num_shards < 1:
        raise ValueError(f'num_shards must be >= 1, got {num_shards}')
    rows: List[Dict[str, Any]] = []
    with open(input_path, encoding='utf-8') as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f'{input_path}:{lineno}: bad JSON: {e}') from e
            if not isinstance(row, dict):
                raise ValueError(
                    f'{input_path}:{lineno}: each row must be a JSON '
                    f'object, got {type(row).__name__}')
            if 'prompt' not in row and 'prompt_ids' not in row:
                raise ValueError(
                    f'{input_path}:{lineno}: row needs a "prompt" '
                    'string or a "prompt_ids" list')
            rows.append(row)
    if not rows:
        raise ValueError(f'{input_path}: no input rows')
    num_shards = min(num_shards, len(rows))
    os.makedirs(out_dir, exist_ok=True)
    base, extra = divmod(len(rows), num_shards)
    counts: List[int] = []
    cursor = 0
    for shard in range(num_shards):
        take = base + (1 if shard < extra else 0)
        with open(os.path.join(out_dir, _shard_file(shard)), 'w',
                  encoding='utf-8') as f:
            for row in rows[cursor:cursor + take]:
                f.write(json.dumps(row) + '\n')
        counts.append(take)
        cursor += take
    meta = {'version': 1, 'num_shards': num_shards,
            'shard_rows': counts, 'total_rows': len(rows),
            'source': os.path.abspath(input_path)}
    with open(os.path.join(out_dir, MANIFEST_FILE), 'w',
              encoding='utf-8') as f:
        json.dump(meta, f, indent=2)
    return Manifest(out_dir)


class Manifest:
    """A built manifest directory: shard metadata + row iteration."""

    def __init__(self, manifest_dir: str) -> None:
        self.dir = os.path.abspath(manifest_dir)
        path = os.path.join(self.dir, MANIFEST_FILE)
        try:
            with open(path, encoding='utf-8') as f:
                meta = json.load(f)
        except FileNotFoundError as e:
            raise ValueError(
                f'{manifest_dir} is not a batch manifest (no '
                f'{MANIFEST_FILE}; build one with '
                f'`sky batch-infer launch --input ...`)') from e
        self.num_shards = int(meta['num_shards'])
        self.shard_rows = [int(n) for n in meta['shard_rows']]
        self.total_rows = int(meta['total_rows'])
        self.source = meta.get('source')

    def rows(self, shard: int) -> Iterator[Tuple[int, Dict[str, Any]]]:
        """(row_idx, row) pairs of one shard, in file order."""
        if not 0 <= shard < self.num_shards:
            raise ValueError(f'shard {shard} out of range '
                             f'[0, {self.num_shards})')
        path = os.path.join(self.dir, _shard_file(shard))
        with open(path, encoding='utf-8') as f:
            for row_idx, line in enumerate(f):
                line = line.strip()
                if line:
                    yield row_idx, json.loads(line)


class ShardLedger:
    """Append-only progress ledger + per-shard output writers.

    Records (one JSON object per line):
      {"kind": "row", "shard": S, "row_idx": I}   committed row
      {"kind": "shard_end", "shard": S}           shard fully committed
    """

    def __init__(self, manifest_dir: str) -> None:
        self.dir = os.path.abspath(manifest_dir)
        self.path = os.path.join(self.dir, LEDGER_FILE)
        self._ledger_f = None
        self._output_fs: Dict[int, Any] = {}

    # ------------------------------------------------------------ replay

    def replay(self) -> Tuple[Set[Tuple[int, int]], Set[int]]:
        """(done_rows, done_shards) from the ledger on disk — the
        resume state.  Torn trailing lines (a write the crash cut
        short) are ignored: the row they named never entered the
        done-set, so it simply re-runs."""
        done_rows: Set[Tuple[int, int]] = set()
        done_shards: Set[int] = set()
        if not os.path.exists(self.path):
            return done_rows, done_shards
        with open(self.path, encoding='utf-8') as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail; the row re-runs
                if rec.get('kind') == 'row':
                    done_rows.add((int(rec['shard']),
                                   int(rec['row_idx'])))
                elif rec.get('kind') == 'shard_end':
                    done_shards.add(int(rec['shard']))
        return done_rows, done_shards

    def progress(self, manifest: Manifest) -> Dict[str, int]:
        """Shards/rows done vs total — what `sky jobs queue` renders
        in its PROGRESS column and `batch-infer status` prints."""
        done_rows, done_shards = self.replay()
        return {'rows_done': len(done_rows),
                'rows_total': manifest.total_rows,
                'shards_done': len(done_shards),
                'shards_total': manifest.num_shards}

    # ------------------------------------------------------------ commit

    def _ledger_handle(self):
        if self._ledger_f is None:
            self._ledger_f = open(self.path, 'a', encoding='utf-8')
        return self._ledger_f

    def _output_handle(self, shard: int):
        f = self._output_fs.get(shard)
        if f is None:
            f = open(os.path.join(self.dir, _output_file(shard)), 'a',
                     encoding='utf-8')
            self._output_fs[shard] = f
        return f

    def _append_ledger(self, record: Dict[str, Any]) -> None:
        f = self._ledger_handle()
        f.write(json.dumps(record) + '\n')
        f.flush()
        os.fsync(f.fileno())

    def commit_row(self, shard: int, row_idx: int,
                   output_row: Dict[str, Any]) -> None:
        """Durably commit one finished row: output append, THEN ledger
        append.  A crash between the two (the `batch.shard_write`
        chaos site) leaves a committed-looking output row with no
        ledger record — the row re-runs on resume and finalize()'s
        dedupe keeps exactly one copy."""
        from skypilot_tpu.chaos import injector  # pylint: disable=import-outside-toplevel
        out = self._output_handle(shard)
        out.write(json.dumps({'shard': shard, 'row_idx': row_idx,
                              **output_row}) + '\n')
        out.flush()
        # Chaos: a raise here is the driver dying mid-commit (output
        # written, ledger not) — the exactly-once seam under test.
        injector.inject('batch.shard_write', shard=shard,
                        row_idx=row_idx)
        self._append_ledger({'kind': 'row', 'shard': shard,
                             'row_idx': row_idx})
        _maybe_journal('batch_row_commit', shard=shard,
                       row_idx=row_idx)

    def finish_shard(self, shard: int) -> None:
        self._append_ledger({'kind': 'shard_end', 'shard': shard})

    def close(self) -> None:
        for f in self._output_fs.values():
            f.close()
        self._output_fs.clear()
        if self._ledger_f is not None:
            self._ledger_f.close()
            self._ledger_f = None

    # ---------------------------------------------------------- finalize

    def finalize(self, manifest: Manifest) -> Dict[str, int]:
        """Exactly-once on rewrite: rewrite every output shard keeping
        the FIRST copy of each (shard, row_idx) — duplicates exist
        precisely when a commit was cut between its two appends — and
        verify the deduped outputs cover the manifest.  Returns
        {'rows', 'duplicates_dropped'}; raises on missing rows (a
        resume that should have re-run them)."""
        self.close()
        total = 0
        dropped = 0
        for shard in range(manifest.num_shards):
            path = os.path.join(self.dir, _output_file(shard))
            seen: Set[int] = set()
            kept: List[str] = []
            if os.path.exists(path):
                with open(path, encoding='utf-8') as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            row_idx = int(json.loads(line)['row_idx'])
                        except (json.JSONDecodeError, KeyError,
                                ValueError):
                            dropped += 1  # torn tail of a cut write
                            continue
                        if row_idx in seen:
                            dropped += 1
                            continue
                        seen.add(row_idx)
                        kept.append(line)
            expected = manifest.shard_rows[shard]
            if len(kept) != expected:
                raise RuntimeError(
                    f'shard {shard}: {len(kept)} output rows != '
                    f'{expected} input rows — resume before '
                    'finalizing')
            tmp = path + '.tmp'
            with open(tmp, 'w', encoding='utf-8') as f:
                for line in kept:
                    f.write(line + '\n')
            os.replace(tmp, path)
            total += len(kept)
        return {'rows': total, 'duplicates_dropped': dropped}

    def output_rows(self, manifest: Manifest,
                    shard: Optional[int] = None
                    ) -> List[Dict[str, Any]]:
        """Parsed output rows (all shards, or one), file order."""
        shards = ([shard] if shard is not None
                  else range(manifest.num_shards))
        rows: List[Dict[str, Any]] = []
        for s in shards:
            path = os.path.join(self.dir, _output_file(s))
            if not os.path.exists(path):
                continue
            with open(path, encoding='utf-8') as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
        return rows
