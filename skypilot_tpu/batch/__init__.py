"""Offline bulk inference (`sky batch-infer`): sharded JSONL manifests
streamed through the serving fleet as QoS class ``batch``, with a
journal-backed per-shard ledger for exactly-once resume and live
weight swap on the replicas (see docs/batch-inference.md)."""
from skypilot_tpu.batch.manifest import (Manifest, ShardLedger,
                                         build_manifest)
from skypilot_tpu.batch.runner import BatchInferJob

__all__ = ['Manifest', 'ShardLedger', 'build_manifest',
           'BatchInferJob']
