"""BatchInferJob: the bulk-inference driver.

Streams a manifest's shards through the serving front door as QoS
class ``batch`` — the router's weighted admission gives interactive
traffic its floor and sheds batch overflow with 429 + Retry-After,
which this driver HONORS (that is the cooperative backoff contract:
batch soaks residual capacity instead of fighting chat traffic).

Runs as a managed job (`sky batch-infer launch` builds a task whose
run command is `python -m skypilot_tpu.batch.runner ...`), so the jobs
controller classifies a dead driver like any preempted task and
relaunches it; the shard ledger (batch/manifest.py) makes the relaunch
a RESUME — committed rows never re-run, half-committed rows re-run and
dedupe on the final rewrite.

Env knobs (see docs/environment-variables.md):
  SKYTPU_BATCH_INFLIGHT           bounded in-flight rows (default 4)
  SKYTPU_BATCH_MAX_RETRIES        per-row retry budget (default 16)
  SKYTPU_BATCH_RETRY_AFTER_CAP_S  cap on honored Retry-After sleeps
  SKYTPU_BATCH_EVENTS             journal the batch lifecycle always
                                  (chaos arms it implicitly)
"""
from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import threading
import time
from typing import Any, Dict, Optional, Set, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.batch import manifest as manifest_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.serve import http_protocol

logger = sky_logging.init_logger(__name__)

# Driver-side progress series (scraped when the driver process exposes
# /metrics; the replica-side skytpu_batch_rows_served_total is what the
# fleet aggregator folds into `sky serve top`).
_M_ROWS = metrics_lib.counter(
    'skytpu_batch_driver_rows_total',
    'Rows the batch driver committed to the shard ledger, by outcome.',
    ('status',))
_M_SHARDS = metrics_lib.counter(
    'skytpu_batch_driver_shards_total',
    'Shards the batch driver finished, by outcome.', ('status',))
_M_RETRIES = metrics_lib.counter(
    'skytpu_batch_driver_retries_total',
    'Row submissions retried after a shed (429/503 + Retry-After) or '
    'a transport error.')


def _env_int(name: str, default: int) -> int:
    try:
        value = int(os.environ.get(name, ''))
    except ValueError:
        return default
    return value if value > 0 else default


def _env_float(name: str, default: float) -> float:
    try:
        value = float(os.environ.get(name, ''))
    except ValueError:
        return default
    return value if value > 0 else default


def default_inflight() -> int:
    return _env_int('SKYTPU_BATCH_INFLIGHT', 4)


def max_retries() -> int:
    return _env_int('SKYTPU_BATCH_MAX_RETRIES', 16)


def retry_after_cap_s() -> float:
    return _env_float('SKYTPU_BATCH_RETRY_AFTER_CAP_S', 10.0)


class RowFailed(RuntimeError):
    """A row exhausted its retry budget; the run stops (resume picks
    the row back up — it never entered the ledger)."""


class BatchInferJob:
    """One driver incarnation over a manifest directory.

    `run()` resumes from the ledger, processes every remaining shard,
    then finalizes (dedupe rewrite) — idempotent: re-running a
    finished job is a no-op that re-verifies the outputs."""

    def __init__(self, manifest_dir: str, endpoint: str, *,
                 max_new_tokens: int = 16,
                 inflight: Optional[int] = None,
                 request_timeout_s: float = 120.0,
                 job_id: Optional[int] = None,
                 task_id: int = 0) -> None:
        self.manifest = manifest_lib.Manifest(manifest_dir)
        self.ledger = manifest_lib.ShardLedger(manifest_dir)
        self.endpoint = endpoint.rstrip('/')
        self.max_new_tokens = int(max_new_tokens)
        self.inflight = max(1, int(inflight if inflight is not None
                                   else default_inflight()))
        self.request_timeout_s = float(request_timeout_s)
        self.max_retries = max_retries()
        self.retry_after_cap_s = retry_after_cap_s()
        # Managed-job context for the PROGRESS column: explicit, else
        # the controller-exported env (jobs/constants.py).
        if job_id is None:
            from skypilot_tpu.jobs import constants as jobs_constants  # pylint: disable=import-outside-toplevel
            raw = os.environ.get(jobs_constants.ENV_MANAGED_JOB_ID)
            job_id = int(raw) if raw and raw.isdigit() else None
        self.job_id = job_id
        self.task_id = int(task_id)
        self.retries = 0
        self._commit_lock = threading.Lock()

    # ------------------------------------------------------------- HTTP

    def _post_row(self, session, row: Dict[str, Any]
                  ) -> Dict[str, Any]:
        """One row through POST /generate as QoS class batch, honoring
        429/503 Retry-After (the router's shed path + a draining
        replica) and retrying transport errors — the driver-side half
        of the LB's retry machinery."""
        import requests  # pylint: disable=import-outside-toplevel
        if 'prompt_ids' in row:
            prompt_ids = [list(map(int, row['prompt_ids']))]
        else:
            # Byte-level convention (models/tokenizer.py fallback):
            # keeps the driver usable against any replica without
            # shipping a tokenizer.
            prompt_ids = [[b + 1 for b in
                           str(row['prompt']).encode('utf-8')]]
        payload = {'prompt_ids': prompt_ids,
                   'max_new_tokens': int(row.get('max_new_tokens',
                                                 self.max_new_tokens))}
        for key in ('temperature', 'top_k', 'seed'):
            if key in row:
                payload[key] = row[key]
        headers = {http_protocol.QOS_CLASS_HEADER: 'batch'}
        attempts = 0
        while True:
            try:
                resp = session.post(
                    self.endpoint + http_protocol.GENERATE,
                    json=payload, headers=headers,
                    timeout=self.request_timeout_s)
            except requests.RequestException as e:
                attempts += 1
                self.retries += 1
                _M_RETRIES.inc()
                if attempts > self.max_retries:
                    raise RowFailed(
                        f'row failed after {attempts} attempts: '
                        f'{e}') from e
                time.sleep(min(0.2 * attempts, 2.0))
                continue
            if resp.status_code in (429, 503):
                # Shed or draining: back off for the stamped
                # Retry-After (the router derives it from the engine's
                # queue-wait p50 when it has one), capped so a stale
                # huge stamp cannot stall the driver.
                attempts += 1
                self.retries += 1
                _M_RETRIES.inc()
                if attempts > self.max_retries:
                    raise RowFailed(
                        f'row shed {attempts} times '
                        f'(HTTP {resp.status_code})')
                try:
                    retry_after = float(
                        resp.headers.get('Retry-After', 1))
                except ValueError:
                    retry_after = 1.0
                time.sleep(max(0.05,
                               min(retry_after,
                                   self.retry_after_cap_s)))
                continue
            if resp.status_code != 200:
                raise RowFailed(f'HTTP {resp.status_code}: '
                                f'{resp.text[:200]}')
            return resp.json()

    # ------------------------------------------------------------ driver

    def _process_row(self, session, shard: int, row_idx: int,
                     row: Dict[str, Any]) -> None:
        result = self._post_row(session, row)
        output = {'tokens': result.get('tokens', [None])[0],
                  'weight_version': result.get('weight_version'),
                  'latency_ms': result.get('latency_ms')}
        # Single-writer commit: output append -> ledger append is the
        # exactly-once seam and must never interleave across rows.
        with self._commit_lock:
            self.ledger.commit_row(shard, row_idx, output)  # skytpu: lint-ok[blocking-under-lock] reason=the lock EXISTS to serialize the output+ledger append pair (the exactly-once seam); commits are one line each and the driver is offline batch, not a request hot path
        _M_ROWS.labels(status='ok').inc()

    def _report_progress(self) -> None:
        if self.job_id is None:
            return
        try:
            from skypilot_tpu.jobs import state as jobs_state  # pylint: disable=import-outside-toplevel
            progress = self.ledger.progress(self.manifest)
            jobs_state.set_batch_progress(
                self.job_id, self.task_id,
                f'{progress["shards_done"]}/'
                f'{progress["shards_total"]} shards '
                f'({progress["rows_done"]}/'
                f'{progress["rows_total"]} rows)')
        except Exception:  # pylint: disable=broad-except
            pass  # progress is advisory; never fail the run over it

    def _run_shard(self, session, pool, shard: int,
                   done_rows: Set[Tuple[int, int]]) -> int:
        todo = [(idx, row) for idx, row in self.manifest.rows(shard)
                if (shard, idx) not in done_rows]
        pending: Set[concurrent.futures.Future] = set()
        committed = 0
        try:
            for row_idx, row in todo:
                while len(pending) >= self.inflight:
                    finished, pending = concurrent.futures.wait(
                        pending,
                        return_when=concurrent.futures.FIRST_COMPLETED)
                    for fut in finished:
                        fut.result()  # re-raise row failures here
                        committed += 1
                pending.add(pool.submit(self._process_row, session,
                                        shard, row_idx, row))
            for fut in concurrent.futures.as_completed(pending):
                fut.result()
                committed += 1
            pending.clear()
            return committed
        finally:
            for fut in pending:
                fut.cancel()

    def run(self) -> Dict[str, Any]:
        import requests  # pylint: disable=import-outside-toplevel
        t0 = time.monotonic()
        done_rows, done_shards = self.ledger.replay()
        resumed = bool(done_rows or done_shards)
        logger.info(
            f'batch-infer: {self.manifest.total_rows} rows in '
            f'{self.manifest.num_shards} shards; resuming with '
            f'{len(done_rows)} rows / {len(done_shards)} shards done'
            if resumed else
            f'batch-infer: {self.manifest.total_rows} rows in '
            f'{self.manifest.num_shards} shards')
        session = requests.Session()
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.inflight) as pool:
            for shard in range(self.manifest.num_shards):
                if shard in done_shards:
                    continue
                manifest_lib._maybe_journal(  # pylint: disable=protected-access
                    'batch_shard_start', shard=shard,
                    resumed=resumed)
                status = 'error'
                try:
                    self._run_shard(session, pool, shard, done_rows)
                    self.ledger.finish_shard(shard)
                    status = 'ok'
                finally:
                    manifest_lib._maybe_journal(  # pylint: disable=protected-access
                        'batch_shard_end', shard=shard, status=status)
                    _M_SHARDS.labels(status=status).inc()
                self._report_progress()
        summary = self.ledger.finalize(self.manifest)
        summary.update(self.ledger.progress(self.manifest))
        summary['retries'] = self.retries
        summary['resumed'] = resumed
        summary['elapsed_s'] = round(time.monotonic() - t0, 3)
        self._report_progress()
        logger.info(f'batch-infer done: {summary}')
        return summary


def main() -> None:
    parser = argparse.ArgumentParser(
        description='Bulk-inference driver (sky batch-infer).')
    parser.add_argument('--manifest-dir', required=True)
    parser.add_argument('--endpoint', required=True,
                        help='Serving front door (LB or replica) URL.')
    parser.add_argument('--max-new-tokens', type=int, default=16)
    parser.add_argument('--inflight', type=int, default=None)
    parser.add_argument('--job-id', type=int, default=None)
    parser.add_argument('--task-id', type=int, default=0)
    args = parser.parse_args()
    job = BatchInferJob(args.manifest_dir, args.endpoint,
                        max_new_tokens=args.max_new_tokens,
                        inflight=args.inflight, job_id=args.job_id,
                        task_id=args.task_id)
    summary = job.run()
    print(json.dumps(summary))


if __name__ == '__main__':
    main()
