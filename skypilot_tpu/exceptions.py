"""Typed exceptions for the framework.

Capability parity with the reference's error taxonomy
(/root/reference/sky/exceptions.py:1-298), redesigned around TPU slices:
provisioning failures carry a failover history over (tpu_type, zone,
capacity_type) triples rather than VM launchables.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional


class SkyTpuError(Exception):
    """Base class for all framework errors."""


class ResourcesUnavailableError(SkyTpuError):
    """No feasible (accelerator, zone, capacity) combination could be provisioned.

    Carries the failover history so callers (managed-jobs recovery, the
    retry_until_up loop) can inspect what was attempted and why it failed.
    """

    def __init__(self,
                 message: str,
                 no_failover: bool = False,
                 failover_history: Optional[List[Exception]] = None) -> None:
        super().__init__(message)
        self.no_failover = no_failover
        self.failover_history: List[Exception] = failover_history or []

    def with_failover_history(
            self, failover_history: List[Exception]
    ) -> 'ResourcesUnavailableError':
        self.failover_history = failover_history
        return self


class ResourcesMismatchError(SkyTpuError):
    """Requested resources do not match the existing cluster's resources."""


class ProvisionPrechecksError(SkyTpuError):
    """Pre-provision validation (quota, credentials, topology) failed."""

    def __init__(self, reasons: List[Exception]) -> None:
        super().__init__(f'Provision prechecks failed: {reasons}')
        self.reasons = reasons


class ProvisionError(SkyTpuError):
    """A cloud API call during provisioning failed."""

    def __init__(self, message: str, *, retryable: bool = True) -> None:
        super().__init__(message)
        self.retryable = retryable


class ClusterNotUpError(SkyTpuError):
    """Operation requires an UP cluster."""

    def __init__(self, message: str, cluster_status: Any = None,
                 handle: Any = None) -> None:
        super().__init__(message)
        self.cluster_status = cluster_status
        self.handle = handle


class ClusterDoesNotExist(SkyTpuError):
    """Named cluster is not in the local state store."""


class ClusterOwnerIdentityMismatchError(SkyTpuError):
    """Cluster was created under a different cloud identity."""


class NotSupportedError(SkyTpuError):
    """Feature is not supported by the selected infra/capacity type."""


class RuntimeVersionSkewError(SkyTpuError):
    """Client and cluster runtime differ by a MAJOR version: the job
    codegen/wire contract may have changed, so exec is refused until
    the cluster runtime is resynced (relaunch or stop/start).  Minor/
    patch skew only warns — the contract is stable within a major."""


class TransientRunnerError(SkyTpuError):
    """A command-runner exec failed in a way that is worth retrying
    (ssh transport blip, connection reset, injected chaos fault) —
    distinct from the command itself exiting non-zero."""

    def __init__(self, message: str, attempts: int = 1) -> None:
        super().__init__(message)
        self.attempts = attempts


class CommandError(SkyTpuError):
    """A remote or local command exited non-zero."""

    def __init__(self, returncode: int, command: str, error_msg: str,
                 detailed_reason: Optional[str] = None) -> None:
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        if len(command) > 100:
            command = command[:100] + '...'
        super().__init__(
            f'Command {command} failed with return code {returncode}.'
            f'\n{error_msg}')


class JobError(SkyTpuError):
    pass


class InvalidTaskError(SkyTpuError):
    """Task spec failed validation."""


class InvalidSkyTpuConfigError(SkyTpuError):
    """~/.skytpu/config.yaml failed schema validation."""


class StorageError(SkyTpuError):
    pass


class StorageSpecError(StorageError, ValueError):
    pass


class StorageBucketCreateError(StorageError):
    pass


class StorageBucketGetError(StorageError):
    pass


class StorageUploadError(StorageError):
    pass


class StorageBucketDeleteError(StorageError):
    pass


class StorageNameError(StorageError, ValueError):
    pass


class StorageSourceError(StorageError, ValueError):
    pass


class FetchClusterInfoError(SkyTpuError):
    """Failed to query live instance info from the cloud."""

    class Reason(enum.Enum):
        HEAD = 'HEAD'
        WORKER = 'WORKER'

    def __init__(self, reason: 'FetchClusterInfoError.Reason') -> None:
        super().__init__(f'Failed to fetch cluster info: {reason.value}')
        self.reason = reason


class NetworkError(SkyTpuError):
    pass


class NoCloudAccessError(SkyTpuError):
    """No infra has valid credentials."""


class ManagedJobReachedMaxRetriesError(SkyTpuError):
    pass


class ManagedJobStatusError(SkyTpuError):
    pass


class ServeUserTerminatedError(SkyTpuError):
    pass


class PortDoesNotExistError(SkyTpuError):
    pass


class UserRequestRejectedByPolicy(SkyTpuError):
    """An admin policy rejected this request."""


class NoClusterLaunchedError(SkyTpuError):
    """Sentinel: failover loop never got as far as launching anything."""


class InvalidClusterNameError(SkyTpuError):
    pass


class CloudUserIdentityError(SkyTpuError):
    pass


class ClusterStatusFetchingError(SkyTpuError):
    pass


class JobExitCode(enum.IntEnum):
    """Process exit codes used by CLI/SDK job-status waiters."""
    SUCCEEDED = 0
    FAILED = 100
    NOT_FINISHED = 101
    NOT_FOUND = 102
    CANCELLED = 103

    @classmethod
    def from_job_status(cls, status: Optional[Any]) -> 'JobExitCode':
        if status is None:
            return cls.NOT_FOUND
        # Local import to avoid a cycle with skylet.job_lib.
        from skypilot_tpu.skylet import job_lib  # pylint: disable=import-outside-toplevel
        if status in (job_lib.JobStatus.SUCCEEDED,):
            return cls.SUCCEEDED
        if status in (job_lib.JobStatus.CANCELLED,):
            return cls.CANCELLED
        if status.is_terminal():
            return cls.FAILED
        return cls.NOT_FINISHED


def serialize_exception(e: Exception) -> Dict[str, Any]:
    """Best-effort JSON-safe description of an exception (for logs/telemetry)."""
    return {
        'type': type(e).__name__,
        'message': str(e),
    }
