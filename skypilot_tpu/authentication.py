"""SSH keypair management + per-cloud key injection.

Parity: /root/reference/sky/authentication.py (generates
~/.sky/sky-key(.pub); injects into cloud metadata).  Here: the keypair
lives under SKYTPU_HOME and is propagated to TPU-VMs via instance
metadata at node-create time (provision/gcp).
"""
from __future__ import annotations

import functools
import os
import subprocess
from typing import Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)

_SSH_KEY_NAME = 'skytpu-key'
DEFAULT_SSH_USER = 'skytpu'


@functools.lru_cache()
def get_or_generate_keys() -> Tuple[str, str]:
    """Returns (private_key_path, public_key_path), generating once."""
    key_dir = common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'keys'))
    private = os.path.join(key_dir, _SSH_KEY_NAME)
    public = private + '.pub'
    if os.path.exists(private) and not os.path.exists(public):
        # NEVER regenerate over an existing private key (live clusters
        # carry its pubkey); re-derive the lost .pub instead.
        _rederive_public_key(private, public)
        return private, public
    if not os.path.exists(private):
        try:
            subprocess.run(
                ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q', '-f',
                 private, '-C', 'skytpu'],
                check=True, capture_output=True)
        except FileNotFoundError:
            # Hermetic images may lack ssh-keygen; generate in-process.
            _generate_keypair_python(private, public)
        os.chmod(private, 0o600)
        logger.info(f'Generated SSH keypair at {private}')
    return private, public


def _rederive_public_key(private: str, public: str) -> None:
    try:
        proc = subprocess.run(['ssh-keygen', '-y', '-f', private],
                              check=True, capture_output=True, text=True)
        with open(public, 'w', encoding='utf-8') as f:
            f.write(proc.stdout.strip() + ' skytpu\n')
        return
    except (FileNotFoundError, subprocess.CalledProcessError):
        pass
    from cryptography.hazmat.primitives import serialization  # pylint: disable=import-outside-toplevel
    with open(private, 'rb') as f:
        key = serialization.load_ssh_private_key(f.read(), password=None)
    pub = key.public_key().public_bytes(
        serialization.Encoding.OpenSSH,
        serialization.PublicFormat.OpenSSH)
    with open(public, 'wb') as f:
        f.write(pub + b' skytpu\n')


def _generate_keypair_python(private: str, public: str) -> None:
    from cryptography.hazmat.primitives import serialization  # pylint: disable=import-outside-toplevel
    from cryptography.hazmat.primitives.asymmetric import ed25519  # pylint: disable=import-outside-toplevel
    key = ed25519.Ed25519PrivateKey.generate()
    with open(private, 'wb') as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.OpenSSH,
            serialization.NoEncryption()))
    pub = key.public_key().public_bytes(
        serialization.Encoding.OpenSSH,
        serialization.PublicFormat.OpenSSH)
    with open(public, 'wb') as f:
        f.write(pub + b' skytpu\n')


def public_key_str() -> str:
    _, public = get_or_generate_keys()
    with open(public, encoding='utf-8') as f:
        return f.read().strip()


def gcp_ssh_metadata(ssh_user: str = DEFAULT_SSH_USER) -> str:
    """The `ssh-keys` metadata value GCP expects: 'user:key-material'."""
    return f'{ssh_user}:{public_key_str()}'
