"""Fleet telemetry aggregation: the controller-side time-series plane.

Until PR 11 every metric in the system was a point-in-time scrape:
`serve status --metrics` showed what a replica said *right now*, the
autoscalers consumed the single latest load probe, and nothing kept
history — so "is TTFT p99 degrading", "is the prefill pool's QPS
trending up", and any SLO question were unanswerable without an
external Prometheus.  This module gives the serve controller its own
small one:

- :class:`TimeSeriesStore` — bounded ring buffers of (ts, value)
  samples per series, keyed by (metric name, full label set).  Both
  retention (seconds) and per-series sample count are capped, so a
  controller supervising a large fleet for months holds a constant
  amount of telemetry.
- :class:`FleetAggregator` — scrapes `GET /metrics` from every READY
  replica and `GET /lb/metrics` from the load balancer on the
  controller's reconcile cadence (interval-gated by
  ``SKYTPU_SERVE_SCRAPE_INTERVAL``), ingests every ``skytpu_*`` series
  into the store with ``replica_id``/``role`` target labels attached
  (so same-named series from different replicas never collapse), and
  derives:

  * **windowed autoscaler signals** (`role_signals`) — smoothed QPS
    and per-replica load over a trailing window, replacing the
    instantaneous signals the role autoscalers used to consume;
  * **per-replica MFU/roofline gauges** (``skytpu_mfu_estimate``) —
    decode tokens/s x the replica's model FLOPs/token over the chip
    roofline (``SKYTPU_CHIP_PEAK_FLOPS``);
  * **windowed latency quantiles** (TTFT/ITL p99 from histogram bucket
    deltas) — what observability/slo.py evaluates burn rates against
    and `sky serve top` displays;
  * **slowest recent traces** — span segments scraped from the
    replicas' `GET /spans?since=`, kept as a bounded worst-N list.

All scraping is best-effort with short timeouts: a wedged replica
degrades the telemetry, never the reconcile loop.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

import requests

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import roles as roles_lib

logger = sky_logging.init_logger(__name__)

# Per-replica roofline gauge the aggregator computes on every scrape:
# the fleet-level counterpart of bench.py's MFU math (ROADMAP item 1's
# ladder reports against this same series).
_M_MFU = metrics_lib.gauge(
    'skytpu_mfu_estimate',
    'Estimated model FLOPs utilization per replica: decode tokens/s x '
    'model FLOPs/token over the chip roofline '
    '(SKYTPU_CHIP_PEAK_FLOPS x num_hosts).',
    ('service', 'replica_id', 'role'))
_M_SCRAPES = metrics_lib.counter(
    'skytpu_fleet_scrapes_total',
    'Fleet telemetry scrape attempts by the controller aggregator, '
    'by outcome (ok / error).', ('outcome',))
_M_SERIES = metrics_lib.gauge(
    'skytpu_fleet_series',
    'Distinct series held in the controller aggregator store.')

# Series ingested from scrapes (everything the fleet exposes).
_INGEST_PREFIX = 'skytpu_'

# Decode-path peak FLOP/s per chip for the MFU estimate; default = TPU
# v5e bf16 (matches bench.py's fallback).  Serving MFU uses 2*params
# FLOPs/token (forward only).
_DEFAULT_PEAK_FLOPS = 197e12


def scrape_interval() -> float:
    return float(os.environ.get('SKYTPU_SERVE_SCRAPE_INTERVAL', '10'))


def retention_s() -> float:
    return float(os.environ.get('SKYTPU_SERVE_METRICS_RETENTION_S',
                                '600'))


def max_samples() -> int:
    return int(os.environ.get('SKYTPU_SERVE_METRICS_MAX_SAMPLES',
                              '512'))


def peak_flops() -> float:
    try:
        return float(os.environ.get('SKYTPU_CHIP_PEAK_FLOPS',
                                    _DEFAULT_PEAK_FLOPS))
    except ValueError:
        return _DEFAULT_PEAK_FLOPS


def _slow_trace_count() -> int:
    return int(os.environ.get('SKYTPU_SERVE_SLOW_TRACES', '16'))


_SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]


class TimeSeriesStore:
    """Bounded (ts, value) ring buffers keyed by (name, labels)."""

    def __init__(self, retention: Optional[float] = None,
                 samples: Optional[int] = None) -> None:
        self._retention = retention
        self._max_samples = samples
        self._series: Dict[_SeriesKey,
                           Deque[Tuple[float, float]]] = {}
        self._lock = threading.Lock()

    def _retention_s(self) -> float:
        return self._retention if self._retention is not None \
            else retention_s()

    def add(self, name: str, labels: Dict[str, Any], ts: float,
            value: float) -> None:
        key = (name, tuple(sorted((str(k), str(v))
                                  for k, v in labels.items())))
        cutoff = ts - self._retention_s()
        with self._lock:
            buf = self._series.get(key)
            if buf is None:
                buf = collections.deque(
                    maxlen=self._max_samples or max_samples())
                self._series[key] = buf
            buf.append((ts, float(value)))
            while buf and buf[0][0] < cutoff:
                buf.popleft()

    def prune(self, now: float) -> None:
        """Drop samples past retention and series that ran dry (a
        retired replica's series must not linger forever)."""
        cutoff = now - self._retention_s()
        with self._lock:
            for key in list(self._series):
                buf = self._series[key]
                while buf and buf[0][0] < cutoff:
                    buf.popleft()
                if not buf:
                    del self._series[key]
            _M_SERIES.set(len(self._series))

    def series(self, name: str, **label_filter: Any
               ) -> List[Tuple[Dict[str, str],
                               List[Tuple[float, float]]]]:
        """Matching series as (labels, samples oldest-first); a filter
        key must equal the series' value to match."""
        want = {str(k): str(v) for k, v in label_filter.items()}
        out = []
        with self._lock:
            for (sname, labels), buf in self._series.items():
                if sname != name:
                    continue
                ldict = dict(labels)
                if any(ldict.get(k) != v for k, v in want.items()):
                    continue
                out.append((ldict, list(buf)))
        return out

    def names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def latest(self, name: str, **label_filter: Any
               ) -> List[Tuple[Dict[str, str], float]]:
        return [(labels, samples[-1][1])
                for labels, samples in self.series(name, **label_filter)
                if samples]

    # -------------------------------------------------- derived views

    @staticmethod
    def _window(samples: List[Tuple[float, float]], window_s: float,
                now: float) -> List[Tuple[float, float]]:
        cutoff = now - window_s
        return [(t, v) for t, v in samples if t >= cutoff]

    def counter_rate(self, name: str, window_s: float, now: float,
                     **label_filter: Any) -> Optional[float]:
        """Summed per-second rate across matching counter series over
        the trailing window.  Counter resets (value drops — a replica
        restart) contribute the post-reset value, Prometheus-style.
        None when no series has two samples in the window."""
        total = 0.0
        seen = False
        for _, samples in self.series(name, **label_filter):
            pts = self._window(samples, window_s, now)
            if len(pts) < 2:
                continue
            increase = 0.0
            for (_, prev), (_, cur) in zip(pts, pts[1:]):
                increase += (cur - prev) if cur >= prev else cur
            dt = pts[-1][0] - pts[0][0]
            if dt > 0:
                total += increase / dt
                seen = True
        return total if seen else None

    def gauge_mean(self, name: str, window_s: float, now: float,
                   **label_filter: Any) -> Optional[float]:
        """Mean of every sample across matching series in the window."""
        values = [v for _, samples in self.series(name, **label_filter)
                  for _, v in self._window(samples, window_s, now)]
        if not values:
            return None
        return sum(values) / len(values)

    def per_series_mean(self, name: str, window_s: float, now: float,
                        **label_filter: Any
                        ) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Windowed mean per matching series (keyed by its labels)."""
        out = {}
        for labels, samples in self.series(name, **label_filter):
            pts = self._window(samples, window_s, now)
            if pts:
                out[tuple(sorted(labels.items()))] = (
                    sum(v for _, v in pts) / len(pts))
        return out

    def bucket_deltas(self, name: str, window_s: float, now: float,
                      **label_filter: Any) -> Dict[float, float]:
        """Cumulative-count increase per histogram bucket bound over
        the window, summed across matching `<name>_bucket` series —
        i.e. the distribution of observations that happened INSIDE the
        window (reset-tolerant like counter_rate)."""
        deltas: Dict[float, float] = {}
        for labels, samples in self.series(f'{name}_bucket',
                                           **label_filter):
            le = labels.get('le')
            if le is None:
                continue
            bound = float('inf') if le == '+Inf' else float(le)
            pts = self._window(samples, window_s, now)
            if len(pts) < 2:
                continue
            increase = 0.0
            for (_, prev), (_, cur) in zip(pts, pts[1:]):
                increase += (cur - prev) if cur >= prev else cur
            deltas[bound] = deltas.get(bound, 0.0) + increase
        return deltas

    def quantile(self, name: str, q: float, window_s: float,
                 now: float, **label_filter: Any) -> Optional[float]:
        """Windowed histogram quantile (metrics.histogram_quantile
        semantics, incl. in-bucket interpolation) from bucket deltas."""
        deltas = self.bucket_deltas(name, window_s, now, **label_filter)
        if not deltas:
            return None
        parsed = {f'{name}_bucket': {
            (('le', '+Inf' if bound == float('inf')
              else repr(bound)),): count
            for bound, count in deltas.items()}}
        return metrics_lib.histogram_quantile(parsed, name, q)

    def binned(self, name: str, window_s: float, bins: int, now: float,
               mode: str = 'mean', **label_filter: Any
               ) -> List[Optional[float]]:
        """The window chopped into `bins` equal slots, oldest first —
        the `sky serve top` sparkline input.  mode 'mean' averages
        gauge samples per bin (summing across series); mode 'rate'
        spreads counter increases across the bins they span.  Empty
        bins are None."""
        if bins < 1:
            return []
        width = window_s / bins
        t0 = now - window_s
        if mode == 'rate':
            # Spread each sample pair's counter increase evenly across
            # the bins it spans, then divide by bin width -> per-second
            # rate per bin.
            totals = [0.0] * bins
            seen = [False] * bins
            for _, samples in self.series(name, **label_filter):
                pts = self._window(samples, window_s, now)
                for (pt, pv), (ct, cv) in zip(pts, pts[1:]):
                    inc = (cv - pv) if cv >= pv else cv
                    lo = max(0, min(bins - 1, int((pt - t0) / width)))
                    hi = max(0, min(bins - 1, int((ct - t0) / width)))
                    for b in range(lo, hi + 1):
                        totals[b] += inc / (hi - lo + 1)
                        seen[b] = True
            return [totals[i] / width if seen[i] else None
                    for i in range(bins)]
        sums: List[List[float]] = [[] for _ in range(bins)]
        # Gauge bins: sum simultaneous series (fleet tokens/s is the
        # sum over replicas), then average within the bin.
        per_bin_series: List[Dict[Tuple, List[float]]] = [
            collections.defaultdict(list) for _ in range(bins)]
        for labels, samples in self.series(name, **label_filter):
            key = tuple(sorted(labels.items()))
            for t, v in self._window(samples, window_s, now):
                b = max(0, min(bins - 1, int((t - t0) / width)))
                per_bin_series[b][key].append(v)
        for b in range(bins):
            if per_bin_series[b]:
                sums[b].append(sum(
                    sum(vs) / len(vs)
                    for vs in per_bin_series[b].values()))
        return [s[0] if s else None for s in sums]


class FleetAggregator:
    """Scrape the fleet into a TimeSeriesStore; derive fleet signals."""

    def __init__(self, service_name: str,
                 store: Optional[TimeSeriesStore] = None,
                 timeout: float = 3.0) -> None:
        self.service_name = service_name
        self.store = store or TimeSeriesStore()
        self.timeout = timeout
        self._last_scrape = 0.0
        self._span_since: Dict[str, float] = {}
        self._slow_traces: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    # ---------------------------------------------------------- scrape

    def maybe_scrape(self, targets: List[Dict[str, Any]],
                     now: Optional[float] = None) -> bool:
        """Interval-gated scrape (the reconcile loop calls this every
        pass; actual scraping honors SKYTPU_SERVE_SCRAPE_INTERVAL)."""
        now = time.time() if now is None else now
        if now - self._last_scrape < scrape_interval():
            return False
        self.scrape_fleet(targets, now)
        return True

    def scrape_fleet(self, targets: List[Dict[str, Any]],
                     now: Optional[float] = None) -> None:
        """One scrape pass over `targets`: dicts with `url`, `kind`
        ('replica' | 'lb'), and for replicas `replica_id`, `role`,
        `num_hosts`."""
        now = time.time() if now is None else now
        self._last_scrape = now
        for target in targets:
            try:
                self._scrape_one(target, now)
                _M_SCRAPES.labels(outcome='ok').inc()
            except (requests.RequestException, ValueError,
                    KeyError, TypeError) as e:
                _M_SCRAPES.labels(outcome='error').inc()
                logger.debug(f'fleet scrape failed for '
                             f'{target.get("url")}: {e}')
        self.store.prune(now)

    def _scrape_one(self, target: Dict[str, Any], now: float) -> None:
        url = target['url'].rstrip('/')
        kind = target.get('kind', 'replica')
        path = (http_protocol.LB_METRICS if kind == 'lb'
                else http_protocol.METRICS)
        resp = requests.get(url + path, timeout=self.timeout)
        resp.raise_for_status()
        parsed = metrics_lib.parse_exposition(resp.text)
        if kind == 'lb':
            extra = {'process': 'lb'}
        else:
            role = self._live_role(target, url)
            extra = {'replica_id': str(target.get('replica_id', '')),
                     'role': role}
        for name, by_labels in parsed.items():
            if not name.startswith(_INGEST_PREFIX):
                continue
            for labels, value in by_labels.items():
                merged = dict(labels)
                merged.update(extra)
                self.store.add(name, merged, now, value)
        if kind == 'replica':
            self._update_mfu(target, parsed, role)
            self._scrape_spans(target, url)

    def _live_role(self, target: Dict[str, Any], url: str) -> str:
        """The replica's CURRENT role, from its health payload.

        Registration-time target labels pin the role a replica was
        LAUNCHED with; after a live role morph (serve/role_morph.py)
        the replica answers with its new role while the controller's
        target dict still says the old one — and every windowed
        per-role signal (the rebalancer's inputs) would keep flowing
        into the stale series.  Falls back to the target label when
        the health probe fails or answers something unparseable."""
        try:
            resp = requests.get(url + '/', timeout=self.timeout)
            live = roles_lib.normalize((resp.json() or {}).get('role'))
            target['role'] = live   # keep span/top labels in step
            return live
        except (requests.RequestException, ValueError, KeyError,
                TypeError, AttributeError):
            return roles_lib.role_of(target)

    def _update_mfu(self, target: Dict[str, Any],
                    parsed: Dict[str, Any], role: str) -> None:
        """skytpu_mfu_estimate{replica_id,role}: decode tokens/s x the
        replica's advertised model FLOPs/token over the slice's
        roofline.  0 when the replica does not advertise FLOPs (user
        containers) — absent data must not read as a good number."""
        def total(name: str) -> float:
            return sum((parsed.get(name) or {}).values())

        tokens_per_s = total('skytpu_engine_decode_tokens_per_s')
        flops_per_token = total('skytpu_engine_model_flops_per_token')
        hosts = max(1, int(target.get('num_hosts') or 1))
        mfu = (tokens_per_s * flops_per_token /
               (peak_flops() * hosts)) if flops_per_token else 0.0
        rid = str(target.get('replica_id', ''))
        _M_MFU.labels(service=self.service_name, replica_id=rid,
                      role=role).set(mfu)
        self.store.add('skytpu_mfu_estimate',
                       {'replica_id': rid, 'role': role},
                       time.time(), mfu)

    def _scrape_spans(self, target: Dict[str, Any], url: str) -> None:
        """Pull new span segments since the last scrape and fold them
        into the bounded slowest-traces list (`sky serve top`'s
        SLOWEST TRACES table)."""
        since = self._span_since.get(url, 0.0)
        resp = requests.get(url + http_protocol.SPANS,
                            params={'since': since or None},
                            timeout=self.timeout)
        if resp.status_code != 200:
            return
        segments = (resp.json() or {}).get('segments') or []
        newest = since
        for seg in segments:
            newest = max(newest, float(seg.get('start') or 0.0))
            seg.setdefault('replica_id', target.get('replica_id'))
            seg.setdefault('role', target.get('role'))
        self._span_since[url] = newest
        keep = _slow_trace_count()
        cutoff = time.time() - self.store._retention_s()  # pylint: disable=protected-access

        def key(seg: Dict[str, Any]):
            # The since= cursor is inclusive (the newest segment comes
            # back on the next scrape): dedupe on identity, keeping
            # the LATER copy (a streaming LB segment's duration is
            # refreshed at relay end).
            return (seg.get('request_id'), seg.get('name'),
                    seg.get('replica_id'), seg.get('attempt'),
                    round(float(seg.get('start') or 0.0), 6))

        with self._lock:
            merged = {key(s): s for s in self._slow_traces + segments
                      if (s.get('start') or 0.0) >= cutoff and
                      s.get('duration_ms') is not None}
            ranked = sorted(merged.values(),
                            key=lambda s: -(s.get('duration_ms') or
                                            0.0))
            self._slow_traces = ranked[:keep]

    # --------------------------------------------------------- signals

    def role_signals(self, role: str, window_s: float = 60.0,
                     now: Optional[float] = None) -> Dict[str, Any]:
        """Smoothed autoscaler inputs for one role pool: windowed QPS
        (LB route counter rate) and per-replica windowed load
        (mean (busy+queued)/slots).  Values are None when the store
        has no data yet — callers fall back to the instantaneous
        signals, so a cold controller behaves exactly as before."""
        now = time.time() if now is None else now
        qps = self.store.counter_rate('skytpu_lb_route_total',
                                      window_s, now, role=role)
        busy = self.store.per_series_mean('skytpu_engine_busy_slots',
                                          window_s, now, role=role)
        queued = self.store.per_series_mean('skytpu_engine_queue_depth',
                                            window_s, now, role=role)
        slots = self.store.per_series_mean('skytpu_engine_slots',
                                           window_s, now, role=role)
        loads: List[float] = []
        for key, mean_busy in busy.items():
            cap = slots.get(key)
            if cap:
                q = queued.get(key, 0.0)
                loads.append(min(1.0, (mean_busy + q) / cap))
        return {'qps': qps, 'loads': loads or None}

    def latency_quantiles(self, window_s: float = 60.0,
                          now: Optional[float] = None,
                          **label_filter: Any) -> Dict[str, Any]:
        now = time.time() if now is None else now

        def ms(v: Optional[float]) -> Optional[float]:
            return None if v is None else round(v * 1e3, 3)

        return {
            'ttft_p50_ms': ms(self.store.quantile(
                'skytpu_engine_ttft_seconds', 0.5, window_s, now,
                **label_filter)),
            'ttft_p99_ms': ms(self.store.quantile(
                'skytpu_engine_ttft_seconds', 0.99, window_s, now,
                **label_filter)),
            'itl_p50_ms': ms(self.store.quantile(
                'skytpu_engine_itl_seconds', 0.5, window_s, now,
                **label_filter)),
            'itl_p99_ms': ms(self.store.quantile(
                'skytpu_engine_itl_seconds', 0.99, window_s, now,
                **label_filter)),
        }

    def slow_traces(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._slow_traces)

    def fleet_snapshot(self, roles: List[str],
                       window_s: float = 120.0, bins: int = 24,
                       now: Optional[float] = None) -> Dict[str, Any]:
        """JSON-ready snapshot for `/controller/telemetry` — what
        `sky serve top` renders: per-role sparkline series + windowed
        quantiles, per-replica MFU, and the slowest recent traces."""
        now = time.time() if now is None else now
        out_roles: Dict[str, Any] = {}
        for role in roles:
            sig = self.role_signals(role, min(60.0, window_s), now)
            out_roles[role] = {
                'qps': sig['qps'],
                'qps_spark': self.store.binned(
                    'skytpu_lb_route_total', window_s, bins, now,
                    mode='rate', role=role),
                'tokens_per_s_spark': self.store.binned(
                    'skytpu_engine_decode_tokens_per_s', window_s,
                    bins, now, role=role),
                'load_spark': self.store.binned(
                    'skytpu_engine_busy_slots', window_s, bins, now,
                    role=role),
                **self.latency_quantiles(min(60.0, window_s), now,
                                         role=role),
            }
        # No decimal rounding: an emulated tiny model's real MFU is
        # ~1e-8 and must not floor to 0.
        mfu = {labels.get('replica_id'): float(f'{value:.3g}')
               for labels, value in self.store.latest(
                   'skytpu_mfu_estimate')}
        # Per-replica tick-phase breakdown (seconds of phase time per
        # wall second over the window; falls back to the cumulative
        # total until two scrapes land) and steady-state recompile
        # counts — `sky serve top`'s TICK-BREAKDOWN / RECOMPILES
        # columns.
        tick_breakdown: Dict[str, Dict[str, float]] = {}
        for labels, value in self.store.latest(
                'skytpu_engine_tick_phase_seconds_sum'):
            rid = labels.get('replica_id')
            phase = labels.get('phase')
            if rid is None or phase is None:
                continue
            rate = self.store.counter_rate(
                'skytpu_engine_tick_phase_seconds_sum',
                min(60.0, window_s), now, phase=phase, replica_id=rid)
            tick_breakdown.setdefault(rid, {})[phase] = (
                rate if rate is not None else value)
        recompiles: Dict[str, float] = {}
        for labels, value in self.store.latest(
                'skytpu_engine_recompiles_total'):
            rid = labels.get('replica_id')
            if rid is None:
                continue
            recompiles[rid] = recompiles.get(rid, 0.0) + value
        # Per-replica WARN+ERROR log rate out of the scraped
        # skytpu_log_records_total counters — `sky serve top`'s ERR/s
        # column.  Deferred import: logs is import-light but keeping
        # the aggregator importable without the serve package matters
        # for analysis tooling.
        from skypilot_tpu.observability import logs as logs_lib  # pylint: disable=import-outside-toplevel
        log_error_rates = logs_lib.error_rates(
            self.store, min(60.0, window_s), now)
        # Batch-infer plane: the replica-side bulk-inference signals
        # (rows served under QoS class batch, live weight-swap epochs)
        # — only present while a batch driver is actually running, so
        # `sky serve top` can hide the BATCH line otherwise.
        batch: Optional[Dict[str, Any]] = None
        batch_rows = self.store.latest('skytpu_batch_rows_served_total')
        if batch_rows:
            rate = self.store.counter_rate(
                'skytpu_batch_rows_served_total',
                min(60.0, window_s), now)
            epochs = {labels.get('replica_id'): int(value)
                      for labels, value in self.store.latest(
                          'skytpu_batch_weight_epoch')}
            swaps = sum(value for _, value in self.store.latest(
                'skytpu_batch_weight_swaps_total'))
            batch = {
                'rows_total': sum(v for _, v in batch_rows),
                'rows_per_s': rate,
                'weight_epochs': epochs,
                'weight_swaps_total': swaps,
            }
        return {'window_s': window_s, 'roles': out_roles, 'mfu': mfu,
                'tick_breakdown': tick_breakdown,
                'recompiles': recompiles,
                'log_error_rates': log_error_rates,
                'batch': batch,
                'slow_traces': self.slow_traces(),
                'series_names': self.store.names()}
