"""Dependency-free metrics core with Prometheus text exposition.

Three instrument kinds (Counter, Gauge, Histogram), each with optional
labels, registered in a process-global `Registry` whose `expose()`
renders the Prometheus text format (text/plain; version=0.0.4) that
`GET /metrics` on the serving fronts returns.

Design points:
- No prometheus_client dependency: the serving image stays minimal and
  the exposition format is small enough to own (HELP/TYPE lines,
  `name{label="value"} value`, histogram `_bucket`/`_sum`/`_count`).
- get-or-create constructors (`counter()`/`gauge()`/`histogram()`):
  module-level wiring can run more than once per process (tests build
  many engines); the same (name, labelnames) pair always resolves to
  the same instrument, and a conflicting redefinition raises instead
  of silently forking the series.
- Bounded label cardinality: each instrument folds label sets beyond
  `max_series` into one `_overflow_` child (logged once) — a buggy
  label (e.g. a raw URL with a query string) degrades the metric, not
  the process.
- Thread safety: every mutation happens under the instrument's lock;
  increments from the engine worker, HTTP threads, and the asyncio
  loop interleave freely (pinned by tests/unit/test_observability.py).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

# Upper bounds (seconds) for latency histograms; chosen to straddle the
# serving SLO range (ms-scale ITL through minutes-scale queue waits).
LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
DEFAULT_BUCKETS = LATENCY_BUCKETS
# Per-instrument label-set cap; beyond it new label sets fold into one
# `_overflow_` series.
MAX_SERIES = 256

_OVERFLOW_KEY = '_overflow_'


def _escape_label_value(value: str) -> str:
    return (value.replace('\\', r'\\').replace('\n', r'\n')
            .replace('"', r'\"'))


def _format_series(name: str, labels: Sequence[Tuple[str, str]],
                   value: float) -> str:
    if labels:
        inner = ','.join(f'{k}="{_escape_label_value(str(v))}"'
                         for k, v in labels)
        return f'{name}{{{inner}}} {_format_value(value)}'
    return f'{name} {_format_value(value)}'


def _format_value(value: float) -> str:
    if value == float('inf'):
        return '+Inf'
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class _Instrument:
    """Base: label-keyed children, overflow folding, a lock."""

    kind = 'untyped'

    def __init__(self, name: str, help_text: str,
                 labelnames: Sequence[str] = (),
                 max_series: int = MAX_SERIES) -> None:
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.max_series = max_series
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}
        self._overflowed = False
        if not self.labelnames:
            self._children[()] = self._new_child()

    # Subclasses return their per-series state object.
    def _new_child(self) -> Any:
        raise NotImplementedError

    def labels(self, *values: Any, **kwvalues: Any) -> '_Instrument':
        """A bound view of this instrument for one label set."""
        if kwvalues:
            if values:
                raise ValueError('pass label values positionally OR by '
                                 'name, not both')
            extra = set(kwvalues) - set(self.labelnames)
            if extra:
                raise ValueError(f'{self.name}: unknown labels {extra}')
            try:
                values = tuple(kwvalues[k] for k in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f'{self.name}: missing label {e}; '
                    f'declared labels are {self.labelnames}') from e
        if len(values) != len(self.labelnames):
            raise ValueError(
                f'{self.name} takes {len(self.labelnames)} label '
                f'value(s) {self.labelnames}, got {len(values)}')
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_series:
                    if not self._overflowed:
                        self._overflowed = True
                        logger.warning(
                            f'metric {self.name}: label cardinality '
                            f'exceeded {self.max_series}; folding new '
                            f'label sets into {_OVERFLOW_KEY!r}')
                    key = (_OVERFLOW_KEY,) * len(self.labelnames)
                    child = self._children.get(key)
                    if child is None:
                        child = self._new_child()
                        self._children[key] = child
                else:
                    child = self._new_child()
                    self._children[key] = child
        return _Bound(self, key, child)

    def _default_child(self) -> Any:
        if self.labelnames:
            raise ValueError(
                f'{self.name} has labels {self.labelnames}; call '
                f'.labels(...) first')
        return self._children[()]

    def series(self) -> Dict[Tuple[str, ...], Any]:
        """Snapshot of label-values -> per-series state (for tests and
        pretty-printers)."""
        with self._lock:
            return dict(self._children)

    def expose_lines(self, const: Sequence[Tuple[str, str]] = ()
                     ) -> List[str]:
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [f'# HELP {self.name} {self.help}',
                f'# TYPE {self.name} {self.kind}']


class _Bound:
    """An instrument bound to one label set: forwards the mutators."""

    def __init__(self, parent: _Instrument, key: Tuple[str, ...],
                 child: Any) -> None:
        self._parent = parent
        self._key = key
        self._child = child

    def inc(self, amount: float = 1.0) -> None:
        self._parent._inc_child(self._child, amount)  # pylint: disable=protected-access

    def dec(self, amount: float = 1.0) -> None:
        self._parent._inc_child(self._child, -amount)  # pylint: disable=protected-access

    def set(self, value: float) -> None:
        self._parent._set_child(self._child, value)  # pylint: disable=protected-access

    def observe(self, value: float) -> None:
        self._parent._observe_child(self._child, value)  # pylint: disable=protected-access

    @property
    def value(self) -> float:
        return self._parent._read_child(self._child)  # pylint: disable=protected-access


class Counter(_Instrument):
    """Monotonically increasing count (use `_total` suffixed names)."""

    kind = 'counter'

    def _new_child(self) -> List[float]:
        return [0.0]

    def _inc_child(self, child: List[float], amount: float) -> None:
        if amount < 0:
            raise ValueError(f'{self.name}: counters only go up '
                             f'(inc {amount})')
        with self._lock:
            child[0] += amount

    def _read_child(self, child: List[float]) -> float:
        with self._lock:
            return child[0]

    def inc(self, amount: float = 1.0) -> None:
        self._inc_child(self._default_child(), amount)

    @property
    def value(self) -> float:
        return self._read_child(self._default_child())

    def expose_lines(self, const: Sequence[Tuple[str, str]] = ()
                     ) -> List[str]:
        lines = self._header()
        with self._lock:
            for key, child in sorted(self._children.items()):
                lines.append(_format_series(
                    self.name,
                    list(const) + list(zip(self.labelnames, key)),
                    child[0]))
        return lines


class Gauge(_Instrument):
    """A value that goes up and down (queue depth, busy slots)."""

    kind = 'gauge'

    def _new_child(self) -> List[float]:
        return [0.0]

    def _inc_child(self, child: List[float], amount: float) -> None:
        with self._lock:
            child[0] += amount

    def _set_child(self, child: List[float], value: float) -> None:
        with self._lock:
            child[0] = float(value)

    def _read_child(self, child: List[float]) -> float:
        with self._lock:
            return child[0]

    def inc(self, amount: float = 1.0) -> None:
        self._inc_child(self._default_child(), amount)

    def dec(self, amount: float = 1.0) -> None:
        self._inc_child(self._default_child(), -amount)

    def set(self, value: float) -> None:
        self._set_child(self._default_child(), value)

    @property
    def value(self) -> float:
        return self._read_child(self._default_child())

    def expose_lines(self, const: Sequence[Tuple[str, str]] = ()
                     ) -> List[str]:
        lines = self._header()
        with self._lock:
            for key, child in sorted(self._children.items()):
                lines.append(_format_series(
                    self.name,
                    list(const) + list(zip(self.labelnames, key)),
                    child[0]))
        return lines


class _HistChild:
    __slots__ = ('counts', 'total', 'count')

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * n_buckets   # per-bucket (non-cumulative)
        self.total = 0.0
        self.count = 0


class Histogram(_Instrument):
    """Observations bucketed by upper bound; exposed cumulatively with
    `le` labels plus `_sum`/`_count` (Prometheus histogram contract)."""

    kind = 'histogram'

    def __init__(self, name: str, help_text: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 max_series: int = MAX_SERIES) -> None:
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError('histogram needs at least one bucket')
        if any(b <= a for a, b in zip(buckets, buckets[1:])):
            raise ValueError(f'duplicate bucket bounds in {buckets}')
        self.buckets = buckets
        super().__init__(name, help_text, labelnames,
                         max_series=max_series)

    def _new_child(self) -> _HistChild:
        return _HistChild(len(self.buckets) + 1)  # +1: the +Inf bucket

    def _observe_child(self, child: _HistChild, value: float) -> None:
        idx = len(self.buckets)
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                idx = i
                break
        with self._lock:
            child.counts[idx] += 1
            child.total += value
            child.count += 1

    def _read_child(self, child: _HistChild) -> float:
        with self._lock:
            return child.count

    def observe(self, value: float) -> None:
        self._observe_child(self._default_child(), value)

    @property
    def count(self) -> int:
        child = self._default_child()
        with self._lock:
            return child.count

    @property
    def sum(self) -> float:
        child = self._default_child()
        with self._lock:
            return child.total

    def bucket_counts(self, *label_values: Any) -> List[int]:
        """Non-cumulative per-bucket counts (last = +Inf overflow)."""
        if self.labelnames:
            key = tuple(str(v) for v in label_values)
            with self._lock:
                child = self._children[key]
                return list(child.counts)
        child = self._default_child()
        with self._lock:
            return list(child.counts)

    def expose_lines(self, const: Sequence[Tuple[str, str]] = ()
                     ) -> List[str]:
        lines = self._header()
        with self._lock:
            for key, child in sorted(self._children.items()):
                base = list(const) + list(zip(self.labelnames, key))
                acc = 0
                for bound, n in zip(self.buckets, child.counts):
                    acc += n
                    lines.append(_format_series(
                        f'{self.name}_bucket',
                        base + [('le', _format_value(bound))], acc))
                acc += child.counts[-1]
                lines.append(_format_series(
                    f'{self.name}_bucket', base + [('le', '+Inf')], acc))
                lines.append(_format_series(f'{self.name}_sum', base,
                                            child.total))
                lines.append(_format_series(f'{self.name}_count', base,
                                            child.count))
        return lines


class Registry:
    """Named instruments -> one exposition document."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Instrument] = {}
        # Constant process-identity labels stamped on EVERY exposed
        # series (replica_id / role / num_hosts on a serving replica):
        # the fleet aggregator's store keys series by their full label
        # set, so without these, same-named series scraped from
        # different replicas would collapse into one.
        self._const_labels: Tuple[Tuple[str, str], ...] = ()

    def set_const_labels(self, labels: Dict[str, Any]) -> None:
        """Install the constant labels appended to every series this
        registry exposes (sorted by label name for a stable format)."""
        with self._lock:
            self._const_labels = tuple(sorted(
                (str(k), str(v)) for k, v in labels.items()))

    def const_labels(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._const_labels)

    def register(self, metric: _Instrument) -> _Instrument:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                raise ValueError(
                    f'metric {metric.name!r} already registered')
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._metrics.get(name)

    def _get_or_create(self, cls, name: str, help_text: str,
                       labelnames: Sequence[str], **kwargs) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls or
                        existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f'metric {name!r} already registered as '
                        f'{type(existing).__name__}'
                        f'{existing.labelnames}; cannot redefine as '
                        f'{cls.__name__}{tuple(labelnames)}')
                return existing
            metric = cls(name, help_text, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_text: str,
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name: str, help_text: str,
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(self, name: str, help_text: str,
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_text,
                                   labelnames, buckets=buckets)

    def expose(self) -> str:
        """The whole registry in Prometheus text format."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
            const = self._const_labels
        lines: List[str] = []
        for metric in metrics:
            lines.extend(metric.expose_lines(const))
        return '\n'.join(lines) + '\n'

    def clear(self) -> None:
        """Drop every instrument (tests only — wiring re-creates its
        instruments through the get-or-create constructors)."""
        with self._lock:
            self._metrics.clear()
            self._const_labels = ()


# The process-global registry every layer reports into; `GET /metrics`
# on the serving fronts exposes exactly this.
REGISTRY = Registry()

CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'


def counter(name: str, help_text: str,
            labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help_text, labelnames)


def gauge(name: str, help_text: str,
          labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help_text, labelnames)


def histogram(name: str, help_text: str,
              labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help_text, labelnames,
                              buckets=buckets)


def expose() -> str:
    return REGISTRY.expose()


def parse_exposition(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str],
                                                        ...], float]]:
    """Parse the text format back into {name: {labels: value}} — used
    by the round-trip tests, the CLI pretty-printer, and the
    bench_serve smoke scrape.  Labels are a sorted tuple of (k, v)."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith('#'):
            continue
        if '{' in line:
            name, rest = line.split('{', 1)
            label_str, value_str = rest.rsplit('} ', 1)
            labels = []
            for part in _split_labels(label_str):
                k, v = part.split('=', 1)
                labels.append((k, v.strip('"')
                               .replace(r'\"', '"')
                               .replace(r'\n', '\n')
                               .replace(r'\\', '\\')))
            key = tuple(sorted(labels))
        else:
            name, value_str = line.rsplit(' ', 1)
            key = ()
        value = float('inf') if value_str == '+Inf' else float(value_str)
        out.setdefault(name.strip(), {})[key] = value
    return out


def histogram_quantile(parsed: Dict[str, Dict[Tuple[Tuple[str, str],
                                                    ...], float]],
                       name: str, q: float) -> Optional[float]:
    """Quantile of an exposed Prometheus histogram, from
    `parse_exposition` output (the CLI tables and the fleet aggregator
    both feed through here).

    Buckets from every label set of `<name>_bucket` are summed per
    upper bound (an aggregated quantile across replicas/roles), then
    the quantile is read Prometheus-style: find the bucket where the
    cumulative count crosses q and interpolate LINEARLY inside it
    (lower edge = the previous bucket's bound, 0 for the first).  A
    quantile landing in the +Inf bucket clamps to the highest finite
    bound.  Returns None without data."""
    buckets = parsed.get(f'{name}_bucket')
    if not buckets:
        return None
    cum: Dict[float, float] = {}
    for labels, value in buckets.items():
        le = dict(labels).get('le')
        if le is None:
            continue
        bound = float('inf') if le == '+Inf' else float(le)
        cum[bound] = cum.get(bound, 0.0) + value
    rows = sorted(cum.items())
    if not rows or rows[-1][1] <= 0:
        return None
    total = rows[-1][1]
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, acc in rows:
        if acc >= target:
            if bound == float('inf'):
                # Prometheus convention: the +Inf bucket has no upper
                # edge to interpolate into; report the highest finite
                # bound (None when every observation overflowed).
                finite = [b for b, _ in rows if b != float('inf')]
                return finite[-1] if finite else None
            if acc == prev_cum:
                return bound
            frac = (target - prev_cum) / (acc - prev_cum)
            return prev_bound + (bound - prev_bound) * max(
                0.0, min(1.0, frac))
        prev_bound, prev_cum = bound, acc
    return rows[-1][0]


def _split_labels(label_str: str) -> Iterable[str]:
    """Split `k1="v1",k2="v2"` respecting escaped quotes."""
    parts, buf, in_quotes, escaped = [], [], False, False
    for ch in label_str:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == '\\':
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_quotes = not in_quotes
            buf.append(ch)
            continue
        if ch == ',' and not in_quotes:
            parts.append(''.join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append(''.join(buf))
    return parts


def start_exposition_server(port: int = 0,
                            registry: Optional[Registry] = None):
    """Standalone `GET /metrics` endpoint over `registry` (default: the
    process-global one); returns (port, shutdown_fn).  Used where no
    serving front exists to piggyback on (bench_serve's smoke scrape,
    training jobs)."""
    import http.server  # pylint: disable=import-outside-toplevel
    reg = registry or REGISTRY

    class Handler(http.server.BaseHTTPRequestHandler):

        def log_message(self, *args):
            del args

        def do_GET(self):
            from skypilot_tpu.serve import http_protocol  # pylint: disable=import-outside-toplevel
            if self.path not in (http_protocol.METRICS, '/'):
                self.send_response(404)
                self.end_headers()
                return
            body = reg.expose().encode()
            self.send_response(200)
            self.send_header('Content-Type', CONTENT_TYPE)
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = http.server.ThreadingHTTPServer(('127.0.0.1', port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd.server_port, httpd.shutdown


class Timer:
    """`with Timer(hist): ...` observes the block's wall time."""

    def __init__(self, hist) -> None:
        self._hist = hist
        self._t0 = 0.0

    def __enter__(self) -> 'Timer':
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *args: Any) -> None:
        self._hist.observe(time.perf_counter() - self._t0)
