"""Continuous profiling plane: tick-phase breakdown + recompile
sentinel for the serving engines.

Two always-on, low-overhead instruments (ISSUE 18):

- **TickProfiler** — a bounded ring of per-tick phase timings.  The
  engine worker marks phase boundaries with `lap()`; each lap is ONE
  monotonic clock read (the previous lap's timestamp is the phase
  start, so phases are exclusive by construction — nested laps, like
  the page-scatter inside a prefill finish, subtract themselves from
  the enclosing phase).  Idle ticks (no recorded phase) never enter
  the ring.  Each retained tick carries a device-memory watermark when
  the backend reports one (`memory_stats()` is None on CPU).  Phase
  durations also feed the process-global
  `skytpu_engine_tick_phase_seconds{phase}` histogram so the fleet
  aggregator sees the breakdown without touching `/profile`.

- **RecompileSentinel** — wraps the engine's resolved jit entries
  (incl. the Pallas kernel path, a closure constant of the wrapped
  step) and watches `fn._cache_size()` after every call: an increase
  means THIS call compiled.  Compiles during warm-up are expected;
  a compile after `steady_after` quiet calls is the classic silent
  TPU perf killer — it bumps `skytpu_engine_recompiles_total{fn}` and
  journals `recompile_detected{fn, shapes}` so the post-mortem names
  the shape that busted the cache.

Knobs: `SKYTPU_PROFILE_RING_TICKS` (ring capacity, default 512),
`SKYTPU_PROFILE_DISABLE` (=1 turns both instruments into no-ops).
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.observability import metrics as metrics_lib

# The complete tick-phase vocabulary (docs/observability.md mirrors
# this table).  A tick records only the phases that ran; decode-step
# and spec-verify are mutually exclusive per tick, slice-sync appears
# only on multi-host replicas.
PHASES = ('admit', 'prefill-chunk', 'decode-step', 'spec-verify',
          'sample', 'page-scatter', 'handoff', 'slice-sync')

DEFAULT_RING_TICKS = 512
# Steady-state threshold: a compile after this many quiet calls of the
# same jit entry is a regression signal, not warm-up.
DEFAULT_STEADY_AFTER = 64

_M_PHASE = metrics_lib.histogram(
    'skytpu_engine_tick_phase_seconds',
    'Engine tick time by phase (exclusive: phases of one tick sum to '
    'the tick duration).',
    ('phase',),
    buckets=(50e-6, 200e-6, 1e-3, 5e-3, 20e-3, 0.1, 0.5))
_M_RECOMPILES = metrics_lib.counter(
    'skytpu_engine_recompiles_total',
    'Steady-state recompilations detected per jit entry (compiles '
    'after the warm-up window — each one is a served-tick stall).',
    ('fn',))
# Pre-bound histogram children: .labels() validates and rebuilds the
# label tuple on every call, which is most of the per-lap cost — the
# phase vocabulary is closed, so bind once.
_PHASE_OBSERVERS = {name: _M_PHASE.labels(phase=name)
                    for name in PHASES}


def profiling_disabled() -> bool:
    return bool(os.environ.get('SKYTPU_PROFILE_DISABLE'))


def ring_ticks_default() -> int:
    raw = os.environ.get('SKYTPU_PROFILE_RING_TICKS')
    try:
        n = int(raw) if raw else DEFAULT_RING_TICKS
    except ValueError:
        n = DEFAULT_RING_TICKS
    return max(1, n)


def serve_journal():
    """The serving flight recorder (`<journal_root>/serve.jsonl`) —
    recompile detections and the tick_profile lifecycle land next to
    the page alloc/free events chaos scenarios already replay."""
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    return events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))


def _default_memory_cb() -> Optional[int]:
    """Device-memory watermark in bytes (None when the backend does
    not report memory stats — CPU jax returns None)."""
    try:
        import jax  # pylint: disable=import-outside-toplevel
        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
    except Exception:  # pylint: disable=broad-except
        return None
    if not stats:
        return None
    peak = stats.get('peak_bytes_in_use', stats.get('bytes_in_use'))
    return int(peak) if peak is not None else None


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class TickProfiler:
    """Per-tick phase timings in a bounded ring.

    Single-writer (the engine worker thread) / multi-reader
    (`snapshot()` from HTTP threads): the in-progress tick is thread
    local to the writer; only the ring append and aggregate updates
    take the lock.
    """

    def __init__(self, *, ring_ticks: Optional[int] = None,
                 disabled: Optional[bool] = None,
                 memory_cb: Optional[Callable[[], Optional[int]]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.disabled = (profiling_disabled() if disabled is None
                         else bool(disabled))
        self.ring_ticks = (ring_ticks_default() if ring_ticks is None
                           else max(1, int(ring_ticks)))
        self._clock = clock
        self._memory_cb = (_default_memory_cb if memory_cb is None
                           else memory_cb)
        self._mem_dead = False   # backend reported nothing; stop asking
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.ring_ticks)
        self._ticks = 0          # non-idle ticks retained (cumulative)
        self._laps = 0           # recorded laps (cumulative)
        self._phase_totals: Dict[str, float] = {}
        self._phase_counts: Dict[str, int] = {}
        self._mem_watermark: Optional[int] = None
        # Worker-thread state for the in-progress tick.
        self._t_tick0 = 0.0
        self._t_last = 0.0
        self._cur: List[Tuple[str, float, float]] = []
        # Self-overhead model: per-lap clock+bookkeeping cost measured
        # once, multiplied by the cumulative lap count in snapshot().
        self._per_lap_s = self._calibrate(clock)

    @staticmethod
    def _calibrate(clock: Callable[[], float]) -> float:
        n = 256
        t0 = time.perf_counter()
        for _ in range(n):
            clock()
        per_read = (time.perf_counter() - t0) / n
        # A lap is one clock read plus a tuple append; double the read
        # cost is a deliberately pessimistic bound.
        return per_read * 2.0

    # ---------------------------------------------- worker-thread API

    def begin_tick(self) -> None:
        if self.disabled:
            return
        now = self._clock()
        self._t_tick0 = now
        self._t_last = now
        self._cur = []

    def lap(self, phase: str, record: bool = True) -> None:
        """Close the interval since the previous lap.  `record=False`
        advances the lap clock without attributing the interval (the
        phase's machinery ran but did no work this tick)."""
        if self.disabled:
            return
        now = self._clock()
        if record:
            self._cur.append((phase, self._t_last - self._t_tick0,
                              now - self._t_last))
        self._t_last = now

    def end_tick(self) -> None:
        """Retain the tick if any phase recorded; idle spins of the
        worker loop never enter the ring."""
        if self.disabled:
            return
        cur = self._cur
        self._cur = []
        if not cur:
            return
        mem = self._sample_mem()
        rec = {
            'ts': time.time(),
            'dur_s': self._t_last - self._t_tick0,
            'phases': cur,
            'mem_bytes': mem,
        }
        with self._lock:
            self._ring.append(rec)
            self._ticks += 1
            self._laps += len(cur)
            for name, _, dur in cur:
                self._phase_totals[name] = (
                    self._phase_totals.get(name, 0.0) + dur)
                self._phase_counts[name] = (
                    self._phase_counts.get(name, 0) + 1)
            if mem is not None and (self._mem_watermark is None or
                                    mem > self._mem_watermark):
                self._mem_watermark = mem
        for name, _, dur in cur:
            obs = _PHASE_OBSERVERS.get(name)
            if obs is None:
                obs = _M_PHASE.labels(phase=name)
            obs.observe(dur)

    def _sample_mem(self) -> Optional[int]:
        if self._mem_dead:
            return None
        mem = self._memory_cb()
        if mem is None:
            self._mem_dead = True
        return mem

    # ------------------------------------------------- reader-side API

    @property
    def ticks(self) -> int:
        with self._lock:
            return self._ticks

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready view: ring, per-phase aggregates + quantiles over
        the ring, memory watermark, and the profiler's own modeled
        overhead (what the ≤3% budget is asserted against)."""
        with self._lock:
            ring = [dict(rec, phases=[list(p) for p in rec['phases']])
                    for rec in self._ring]
            totals = dict(self._phase_totals)
            counts = dict(self._phase_counts)
            ticks = self._ticks
            laps = self._laps
            watermark = self._mem_watermark
        durs_by_phase: Dict[str, List[float]] = {}
        for rec in ring:
            for name, _, dur in rec['phases']:
                durs_by_phase.setdefault(name, []).append(dur)
        phases: Dict[str, Dict[str, Any]] = {}
        for name, total in sorted(totals.items()):
            durs = sorted(durs_by_phase.get(name, ()))
            phases[name] = {
                'count': counts.get(name, 0),
                'total_s': total,
                'p50_s': _quantile(durs, 0.5),
                'p90_s': _quantile(durs, 0.9),
                'p99_s': _quantile(durs, 0.99),
                'max_s': durs[-1] if durs else None,
            }
        last_mem = next((rec['mem_bytes'] for rec in reversed(ring)
                         if rec.get('mem_bytes') is not None), None)
        return {
            'enabled': not self.disabled,
            'ring_ticks': self.ring_ticks,
            'ticks': ticks,
            'phases': phases,
            'ring': ring,
            'device_memory': {'watermark_bytes': watermark,
                              'last_bytes': last_mem},
            'overhead_s': laps * self._per_lap_s,
        }


class RecompileSentinel:
    """Counts compilations per wrapped jit entry and flags the
    steady-state ones (compile after `steady_after` quiet calls)."""

    def __init__(self, *, steady_after: int = DEFAULT_STEADY_AFTER,
                 journal_factory: Optional[Callable[[], Any]] = None,
                 disabled: Optional[bool] = None) -> None:
        self.disabled = (profiling_disabled() if disabled is None
                         else bool(disabled))
        self.steady_after = int(steady_after)
        self._journal_factory = (serve_journal if journal_factory is None
                                 else journal_factory)
        self._lock = threading.Lock()
        self._fns: Dict[str, Dict[str, Any]] = {}

    def wrap(self, name: str, fn):
        """Pass-through wrapper; after every call, an O(1) cache-size
        probe decides whether THIS call compiled.  Shape signatures
        are only computed on a detected compile — the hot path pays
        one lock and one `len()` probe."""
        if self.disabled or fn is None:
            return fn
        with self._lock:
            self._fns.setdefault(name, {
                'calls': 0, 'compiles': 0, 'steady_recompiles': 0,
                'quiet_calls': 0, 'signatures': {},
                'cache_size': None,
            })

        def wrapped(*args, **kwargs):
            out = fn(*args, **kwargs)
            self._after_call(name, fn, args)
            return out

        wrapped.__name__ = name
        wrapped.__wrapped__ = fn
        return wrapped

    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        try:
            return int(fn._cache_size())  # pylint: disable=protected-access
        except Exception:  # pylint: disable=broad-except
            return None

    @staticmethod
    def _signature(args, limit: int = 16) -> str:
        """Compact abstract signature of a call's positional args:
        dtype[shape] per array leaf, capped so a full params pytree
        does not explode the journal line."""
        try:
            import jax  # pylint: disable=import-outside-toplevel
            leaves = jax.tree_util.tree_leaves(args)
        except Exception:  # pylint: disable=broad-except
            leaves = list(args)
        parts: List[str] = []
        for leaf in leaves:
            shape = getattr(leaf, 'shape', None)
            if shape is not None:
                dtype = getattr(leaf, 'dtype', '?')
                dims = ','.join(str(d) for d in shape)
                parts.append(f'{dtype}[{dims}]')
            else:
                parts.append(type(leaf).__name__)
        if len(parts) > limit:
            parts = parts[:limit] + [f'...+{len(parts) - limit} leaves']
        return '(' + ', '.join(parts) + ')'

    def _after_call(self, name: str, fn, args) -> None:
        size = self._cache_size(fn)
        steady_hit = None
        with self._lock:
            st = self._fns[name]
            st['calls'] += 1
            if size is not None:
                compiled = (st['cache_size'] is not None and
                            size > st['cache_size'])
                first = st['cache_size'] is None and size > 0
                st['cache_size'] = size
                compiled = compiled or first
            else:
                # No cache probe on this callable: fall back to the
                # signature set (pay the signature on every call).
                sig = self._signature(args)
                compiled = sig not in st['signatures']
                if compiled:
                    st['signatures'][sig] = 0
            if compiled:
                st['compiles'] += 1
                sig = self._signature(args)
                st['signatures'][sig] = st['signatures'].get(sig, 0) + 1
                quiet = st['quiet_calls']
                st['quiet_calls'] = 0
                if quiet >= self.steady_after:
                    st['steady_recompiles'] += 1
                    steady_hit = (sig, quiet)
            else:
                st['quiet_calls'] += 1
        if steady_hit is None:
            return
        sig, quiet = steady_hit
        _M_RECOMPILES.labels(fn=name).inc()
        try:
            journal = self._journal_factory()
        except Exception:  # pylint: disable=broad-except
            journal = None
        if journal is not None:
            journal.append('recompile_detected', fn=name, shapes=sig,
                           quiet_calls=quiet)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {}
            for name, st in sorted(self._fns.items()):
                sigs = dict(list(st['signatures'].items())[:8])
                out[name] = {
                    'calls': st['calls'],
                    'compiles': st['compiles'],
                    'steady_recompiles': st['steady_recompiles'],
                    'signatures': sigs,
                }
        out_total = sum(v['steady_recompiles'] for v in out.values())
        return {'fns': out, 'steady_recompiles_total': out_total,
                'steady_after': self.steady_after,
                'enabled': not self.disabled}


# --------------------------------------------------------------- exports

def collapsed_stacks(snapshot: Dict[str, Any],
                     root: str = 'engine') -> str:
    """Brendan-Gregg collapsed-stack lines (`engine;phase count_us`)
    from a profiler snapshot — pipe into any flamegraph tool."""
    lines = []
    for name, agg in sorted(snapshot.get('phases', {}).items()):
        us = int(round(float(agg.get('total_s') or 0.0) * 1e6))
        lines.append(f'{root};{name} {us}')
    return '\n'.join(lines) + ('\n' if lines else '')


def chrome_trace(snapshot: Dict[str, Any], *, pid: int = 0,
                 tid: int = 0) -> Dict[str, Any]:
    """Chrome trace-event JSON (`chrome://tracing` / Perfetto) from a
    profiler snapshot's ring: one complete ('X') event per recorded
    phase, plus a device-memory counter track when watermarks exist."""
    events: List[Dict[str, Any]] = []
    for rec in snapshot.get('ring', ()):
        base_us = float(rec.get('ts', 0.0)) * 1e6
        for entry in rec.get('phases', ()):
            name, rel, dur = entry[0], float(entry[1]), float(entry[2])
            events.append({
                'name': name, 'cat': 'engine-tick', 'ph': 'X',
                'ts': base_us + rel * 1e6,
                'dur': max(dur * 1e6, 0.01),
                'pid': pid, 'tid': tid, 'args': {},
            })
        mem = rec.get('mem_bytes')
        if mem is not None:
            events.append({
                'name': 'device_memory', 'cat': 'engine-tick',
                'ph': 'C', 'ts': base_us, 'pid': pid, 'tid': tid,
                'args': {'bytes_in_use': int(mem)},
            })
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}
