"""Machine-readable protocol table for paired journal events.

The flight-recorder journals (observability/events.py) carry paired
lifecycle events — `<base>_start`/`<base>_end`, `kv_pages_alloc`/
`kv_pages_free`, `rank_start`/`rank_exit` — that two independent
consumers must agree on:

- the chaos invariant checkers (`chaos/invariants.py`) replay journals
  and demand that every opened lifecycle terminates with an allowed
  terminal status;
- `sky lint`'s journal-protocol pass (analysis/passes/
  journal_protocol.py) statically verifies every emit site against
  this table: a paired event the table does not name, a `_start` whose
  `_end` is not guaranteed on exception paths, or an end emitted with
  a status outside the allowed set is a finding.

This module is pure data (no imports from the package) so both the
runtime checkers and the AST-only lint plane can share it.  Scopes:

- ``invocation`` — start and end belong to ONE function invocation;
  the end must be reachable on exception paths (a `finally`/`except`
  emit, or the ControlSpan context manager).  Lint enforces this.
- ``process`` — a state machine spanning calls or processes (a drain
  opened by the controller and closed by the drain monitor, an SLO
  breach opened on one evaluate() and closed on a later one).  Only
  journal replay (the invariants) can check these.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

SCOPE_INVOCATION = 'invocation'
SCOPE_PROCESS = 'process'


@dataclasses.dataclass(frozen=True)
class PairedEvents:
    """One paired-event lifecycle."""
    name: str                 # lifecycle name (usually the shared base)
    start: str                # opening event
    end: str                  # terminal event
    scope: str                # SCOPE_INVOCATION | SCOPE_PROCESS
    # The end event's terminal-status field and its allowed literal
    # values (None = any / dynamic values like exception type names).
    status_field: Optional[str] = None
    statuses: Optional[Tuple[str, ...]] = None


def _pair(name: str, scope: str,
          start: Optional[str] = None, end: Optional[str] = None,
          status_field: Optional[str] = None,
          statuses: Optional[Tuple[str, ...]] = None) -> PairedEvents:
    return PairedEvents(name=name,
                        start=start or f'{name}_start',
                        end=end or f'{name}_end',
                        scope=scope, status_field=status_field,
                        statuses=statuses)


# The complete paired-event protocol.  Adding a new `<base>_start` /
# `<base>_end` (or alloc/free-style) lifecycle anywhere in the package
# requires a row here — `skytpu lint` fails otherwise — which is what
# keeps the chaos invariants and the emitters from drifting apart.
PAIRS: Tuple[PairedEvents, ...] = (
    # Control-plane phases (ControlSpan context-manager spans: the end
    # is guaranteed by __exit__, status 'ok' or the exception name).
    _pair('launch', SCOPE_INVOCATION),
    _pair('exec', SCOPE_INVOCATION),
    _pair('optimize', SCOPE_INVOCATION),
    _pair('provision', SCOPE_INVOCATION),
    _pair('sync_workdir', SCOPE_INVOCATION),
    _pair('sync_file_mounts', SCOPE_INVOCATION),
    _pair('setup', SCOPE_INVOCATION),
    # Provisioning lifecycles (direct appends).
    _pair('provision_attempt', SCOPE_INVOCATION,
          status_field='status', statuses=('ok', 'fail')),
    _pair('queued_wait', SCOPE_INVOCATION, status_field='status',
          statuses=('granted', 'timeout', 'error')),
    # Managed-jobs lifecycles.
    _pair('task', SCOPE_INVOCATION),
    _pair('recovery', SCOPE_INVOCATION),
    # Cluster-job gang execution.
    _pair('gang', SCOPE_INVOCATION, status_field='status',
          statuses=('ok', 'fail', 'error')),
    _pair('rank', SCOPE_PROCESS, start='rank_start', end='rank_exit'),
    # Training checkpoints (async writer thread).
    _pair('checkpoint_save', SCOPE_INVOCATION),
    # Serving lifecycles.
    _pair('replica_drain', SCOPE_PROCESS, status_field='reason',
          statuses=('drained', 'timeout', 'dead')),
    _pair('slo_burn', SCOPE_PROCESS),
    _pair('kv_handoff', SCOPE_INVOCATION, status_field='status',
          statuses=('ok', 'fallback', 'error')),
    _pair('kv_pages', SCOPE_PROCESS, start='kv_pages_alloc',
          end='kv_pages_free'),
    # Router tier + QoS (ISSUE 15).  qos_request brackets one request's
    # pass through a router instance's weighted admission: 'ok' =
    # admitted and served, 'shed' = over the class's in-flight share
    # (429 + Retry-After), 'error' = admitted but failed downstream.
    _pair('qos_request', SCOPE_INVOCATION, status_field='status',
          statuses=('ok', 'shed', 'error')),
    # router_instance brackets one router instance's life in the tier
    # (spawn -> scale_down/killed/shutdown).
    _pair('router_instance', SCOPE_PROCESS, status_field='reason',
          statuses=('scale_down', 'killed', 'shutdown')),
    # Dynamic roles (ISSUE 17).  role_rebalance brackets one
    # controller rebalance pass pushing fractional budgets to the
    # fleet (end guaranteed by try/finally: 'ok' = every push landed,
    # 'partial' = some replicas refused/unreachable, 'error' = the
    # pass itself raised).
    _pair('role_rebalance', SCOPE_INVOCATION, status_field='status',
          statuses=('ok', 'partial', 'error')),
    # role_morph brackets one live role change (scoped drain ->
    # prefix handoff -> budget swap -> re-register): a state machine
    # spanning controller ticks, closed by the morph driver with the
    # outcome.
    _pair('role_morph', SCOPE_PROCESS, status_field='status',
          statuses=('ok', 'timeout', 'error')),
    # Continuous profiling (ISSUE 18).  tick_profile brackets one
    # engine worker incarnation's profiling ring (end guaranteed by
    # try/finally: 'ok' = drained/stopped, 'error' = the worker died
    # and failed the engine); recompile_detected is a point event the
    # sentinel journals alongside it.
    _pair('tick_profile', SCOPE_INVOCATION, status_field='status',
          statuses=('ok', 'error')),
    # Fleet log plane (ISSUE 19).  log_error_spike brackets one
    # replica's WARN+ERROR-rate excursion above the spike threshold
    # (same fast/slow multi-window shape as slo_burn; the controller's
    # LogSpikeTracker journals both edges each reconcile pass).
    _pair('log_error_spike', SCOPE_PROCESS),
    # Bulk inference (ISSUE 20).  batch_shard brackets one shard's
    # processing by the batch driver; a driver killed mid-shard leaves
    # a dangling start that the RESUMED driver re-opens and closes —
    # a state machine spanning processes, so the batch_exactly_once
    # invariant (not lint) checks closure.  'ok' = every row committed,
    # 'error' = the shard loop raised (resume will retry it).
    _pair('batch_shard', SCOPE_PROCESS, status_field='status',
          statuses=('ok', 'error')),
    # weight_swap brackets one live checkpoint swap on a replica
    # (POST /weights_swap; end guaranteed by try/finally): 'ok' = the
    # engine serves the new epoch, 'error' = restore/swap failed and
    # the old weights keep serving.  batch_row_commit point events
    # ride alongside in the same journal.
    _pair('weight_swap', SCOPE_INVOCATION, status_field='status',
          statuses=('ok', 'error')),
)

BY_NAME: Dict[str, PairedEvents] = {p.name: p for p in PAIRS}
BY_START: Dict[str, PairedEvents] = {p.start: p for p in PAIRS}
BY_END: Dict[str, PairedEvents] = {p.end: p for p in PAIRS}


def pair_for_event(event: str) -> Optional[PairedEvents]:
    """The lifecycle an event opens or closes (None for point
    events)."""
    return BY_START.get(event) or BY_END.get(event)
