"""Control-plane flight recorder: event journal + spans + fleet metrics.

PR 3 gave the data plane (serving, training) metrics and request
tracing; this module gives the control plane — the part the paper is
about — a durable, queryable record of every orchestration decision:

- :class:`EventJournal`: an append-only JSONL journal, one file per
  cluster / managed job / skylet under ``$SKYTPU_HOME/events/``, with
  size-based rotation and a bounded in-process tail.  A failed or slow
  `launch` stays diagnosable after the processes are gone.
- :class:`ControlSpan`: a context manager that journals
  ``<name>_start`` / ``<name>_end`` (with duration + status) and
  mirrors the finished span into the Chrome-trace timeline
  (utils/timeline.py), so launch phases render next to request spans.
- Fleet-health instruments (get-or-create accessors into the
  process-global metrics registry): ``skytpu_provision_*``,
  ``skytpu_gang_*``, ``skytpu_skylet_*``, ``skytpu_jobs_*``.

Journal writes are best-effort by design: the flight recorder must
never be the reason an orchestration action fails, so I/O errors are
swallowed (debug-logged) and a corrupt line is skipped on read.

Event schema (one JSON object per line):

    {"ts": <epoch seconds>, "seq": <per-process counter>,
     "event": "<type>", ...free-form fields...}

``*_end`` events carry ``status`` ('ok' or the exception class name)
and ``duration_s``.  Surfaced via `sky status --events <cluster>` and
`sky jobs events <id>`; exportable as a Chrome trace.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)

# Per-journal size cap before rotation to `<path>.1` (one rotation
# kept: current + previous generation bound disk per scope).
DEFAULT_MAX_BYTES = 5 * 1024 * 1024
_MAX_BYTES_ENV = 'SKYTPU_EVENT_JOURNAL_MAX_BYTES'
# Events kept in the in-process tail per journal.
TAIL_LEN = 256

# Upper bounds (seconds) for control-plane waits: queued-capacity
# grants and preemption recoveries run minutes-to-hours, far beyond the
# serving-latency buckets.
LONG_WAIT_BUCKETS = (1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                     1200.0, 1800.0, 3600.0, 7200.0)


def _max_bytes() -> int:
    try:
        return int(os.environ.get(_MAX_BYTES_ENV, DEFAULT_MAX_BYTES))
    except ValueError:
        return DEFAULT_MAX_BYTES


class EventJournal:
    """Append-only JSONL journal for one scope (cluster / job / skylet).

    Thread-safe; safe for concurrent appenders from multiple processes
    (O_APPEND line writes; ordering across processes is by timestamp).
    """

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 tail_len: int = TAIL_LEN) -> None:
        self.path = path
        self._max_bytes = max_bytes
        self._lock = threading.Lock()
        self._tail: Deque[Dict[str, Any]] = collections.deque(
            maxlen=tail_len)
        self._seq = itertools.count()

    def append(self, event: str, **fields: Any) -> Dict[str, Any]:
        """Record one event; returns the record (even if the disk write
        failed — the in-process tail always gets it)."""
        record: Dict[str, Any] = {'ts': time.time(),
                                  'seq': next(self._seq),
                                  'event': event}
        record.update(fields)
        with self._lock:
            self._tail.append(record)
            try:
                self._maybe_rotate()
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                # skytpu: lint-ok[blocking-under-lock] reason=this lock EXISTS to serialize the O_APPEND line write; appends are one bounded line and callers are never on a request hot path
                with open(self.path, 'a', encoding='utf-8') as f:
                    f.write(json.dumps(record, default=str) + '\n')
            except OSError as e:
                logger.debug(f'event journal append failed '
                             f'({self.path}): {e}')
        return record

    def _maybe_rotate(self) -> None:
        limit = self._max_bytes if self._max_bytes is not None \
            else _max_bytes()
        try:
            if os.path.getsize(self.path) < limit:
                return
        except OSError:
            return  # no file yet
        os.replace(self.path, self.path + '.1')

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-last snapshot of the in-process tail."""
        with self._lock:
            events = list(self._tail)
        return events[-n:] if n else events

    def read(self) -> List[Dict[str, Any]]:
        """All events on disk (rotated generation first), ts-ordered.
        Corrupt lines are skipped, not fatal."""
        events: List[Dict[str, Any]] = []
        for path in (self.path + '.1', self.path):
            try:
                with open(path, encoding='utf-8') as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            events.append(json.loads(line))
                        except ValueError:
                            continue
            except OSError:
                continue
        events.sort(key=lambda e: e.get('ts', 0.0))
        return events


# ------------------------------------------------------------- registry

_journals: Dict[str, EventJournal] = {}
_journals_lock = threading.Lock()


def journal_root() -> str:
    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    return os.path.join(common_utils.skytpu_home(), 'events')


def get_journal(path: str) -> EventJournal:
    """Get-or-create the journal for `path` (one instance per path, so
    the in-process tail and seq counter are shared across call sites)."""
    with _journals_lock:
        journal = _journals.get(path)
        if journal is None:
            journal = EventJournal(path)
            _journals[path] = journal
        return journal


def cluster_journal(cluster_name: str) -> EventJournal:
    """Launch/provision/teardown events of one cluster (client side)."""
    return get_journal(os.path.join(journal_root(), 'clusters',
                                    f'{cluster_name}.jsonl'))


def job_journal(job_id: int) -> EventJournal:
    """Recovery/preemption events of one managed job (controller side)."""
    return get_journal(os.path.join(journal_root(), 'managed_jobs',
                                    f'{job_id}.jsonl'))


def cluster_job_journal(job_id: int) -> EventJournal:
    """Gang events of one cluster job (written on the head host by the
    gang supervisor; distinct namespace from managed jobs)."""
    return get_journal(os.path.join(journal_root(), 'cluster_jobs',
                                    f'{job_id}.jsonl'))


def skylet_journal() -> EventJournal:
    """Skylet event-loop ticks on this host."""
    return get_journal(os.path.join(journal_root(), 'skylet.jsonl'))


def training_journal() -> EventJournal:
    """Training-side control events on this host (async checkpoint
    saves, elastic resume/resize) — written by user-code processes that
    share this SKYTPU_HOME, so a managed job's checkpoint timeline lands
    next to the controller's recovery timeline."""
    return get_journal(os.path.join(journal_root(), 'training.jsonl'))


def cluster_events(cluster_name: str) -> List[Dict[str, Any]]:
    return cluster_journal(cluster_name).read()


def job_events(job_id: int) -> List[Dict[str, Any]]:
    return job_journal(job_id).read()


def cluster_job_events(job_id: int) -> List[Dict[str, Any]]:
    return cluster_job_journal(job_id).read()


# ----------------------------------------------------------------- spans


class ControlSpan:
    """Journal a control-plane phase as start/end events and mirror the
    finished span into the Chrome-trace timeline.

    The start event makes crashes diagnosable (a `_start` without its
    `_end` marks where the process died); the end event carries
    duration and status.  `journal=None` degrades to timeline-only.
    """

    def __init__(self, journal: Optional[EventJournal], name: str,
                 **fields: Any) -> None:
        self._journal = journal
        self._name = name
        self._fields = dict(fields)
        self._t0 = 0.0
        self._wall0 = 0.0

    def add(self, **fields: Any) -> None:
        """Attach fields discovered mid-span (they ride on the end
        event), e.g. the job id a launch produced."""
        self._fields.update(fields)

    def __enter__(self) -> 'ControlSpan':
        self._t0 = time.monotonic()
        self._wall0 = time.time()
        if self._journal is not None:
            # skytpu: lint-ok[journal-computed-name] reason=span names are literals at every ControlSpan call site; the journal-events pass resolves them there as <name>_start/_end
            self._journal.append(f'{self._name}_start', **self._fields)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        duration = time.monotonic() - self._t0
        status = 'ok' if exc_type is None else exc_type.__name__
        fields = dict(self._fields)
        if exc is not None:
            fields.setdefault('error', str(exc)[:500])
        if self._journal is not None:
            # skytpu: lint-ok[journal-computed-name] reason=span names are literals at every ControlSpan call site; the journal-events pass resolves them there as <name>_start/_end
            self._journal.append(f'{self._name}_end', status=status,
                                 duration_s=round(duration, 6), **fields)
        timeline.add_complete_event(
            f'control:{self._name}', self._wall0, duration,
            args={'status': status, **{k: v for k, v in fields.items()
                                       if isinstance(v, (str, int,
                                                         float, bool))}},
            cat='control')
        return False


# ------------------------------------------------------------ rendering


def format_timeline(events: List[Dict[str, Any]]) -> List[str]:
    """Human-readable timeline lines for `status --events` /
    `jobs events`: wall clock, offset from the first event, event name,
    then the remaining fields as k=v."""
    if not events:
        return []
    t0 = events[0].get('ts', 0.0)
    lines = []
    for e in events:
        ts = e.get('ts', 0.0)
        clock = time.strftime('%H:%M:%S', time.localtime(ts))
        ms = int((ts % 1) * 1000)
        extras = ' '.join(
            f'{k}={e[k]}' for k in e
            if k not in ('ts', 'seq', 'event') and e[k] is not None)
        lines.append(f'{clock}.{ms:03d}  +{ts - t0:8.3f}s  '
                     f'{e.get("event", "?"):<28s} {extras}'.rstrip())
    return lines


def to_chrome_trace_events(events: List[Dict[str, Any]]
                           ) -> List[Dict[str, Any]]:
    """Journal records -> Chrome trace events: `*_end` records with a
    duration become 'X' complete events (placed at their start time);
    everything else becomes an instant marker."""
    out = []
    for e in events:
        name = e.get('event', '?')
        ts = float(e.get('ts', 0.0))
        args = {k: v for k, v in e.items()
                if k not in ('ts', 'seq', 'event')}
        if name.endswith('_end') and 'duration_s' in e:
            duration = float(e['duration_s'])
            out.append({'name': name[:-len('_end')], 'cat': 'control',
                        'ph': 'X',
                        'ts': int((ts - duration) * 1e6),
                        'dur': max(0, int(duration * 1e6)),
                        'pid': 0, 'tid': 0, 'args': args})
        else:
            out.append({'name': name, 'cat': 'control', 'ph': 'i',
                        's': 'p', 'ts': int(ts * 1e6),
                        'pid': 0, 'tid': 0, 'args': args})
    return out


def export_chrome_trace(events: List[Dict[str, Any]], path: str) -> None:
    timeline.write_trace(path, to_chrome_trace_events(events))


# ---------------------------------------------------- fleet instruments
# Get-or-create accessors (module-level wiring may run repeatedly per
# process; the registry resolves the same name to the same instrument).


def provision_attempts() -> metrics.Counter:
    return metrics.counter(
        'skytpu_provision_attempts_total',
        'Per-zone provision attempts made by the failover loop',
        labelnames=('cloud',))


def provision_failovers() -> metrics.Counter:
    return metrics.counter(
        'skytpu_provision_failover_total',
        'Provision attempts that failed and triggered failover, by '
        'failure class', labelnames=('reason',))


def provision_wait_hist() -> metrics.Histogram:
    return metrics.histogram(
        'skytpu_provision_wait_seconds',
        'Queued-resource capacity wait until granted or timed out',
        buckets=LONG_WAIT_BUCKETS)


def gang_ranks_gauge() -> metrics.Gauge:
    return metrics.gauge('skytpu_gang_ranks',
                         'Ranks in the most recent gang run')


def gang_rank_exits() -> metrics.Counter:
    return metrics.counter('skytpu_gang_rank_exits_total',
                           'Gang rank exits by return code',
                           labelnames=('code',))


def gang_abort_hist() -> metrics.Histogram:
    return metrics.histogram(
        'skytpu_gang_abort_seconds',
        'First rank failure to all surviving ranks terminated')


def skylet_tick_hist() -> metrics.Histogram:
    return metrics.histogram('skytpu_skylet_tick_seconds',
                             'Skylet event run() wall time',
                             labelnames=('event',))


def skylet_event_failures() -> metrics.Counter:
    return metrics.counter('skytpu_skylet_event_failures_total',
                           'Skylet event run() raised',
                           labelnames=('event',))


def jobs_preemptions() -> metrics.Counter:
    return metrics.counter(
        'skytpu_jobs_preemptions_total',
        'Managed-job cluster preemptions detected by the controller')


def jobs_recovery_hist() -> metrics.Histogram:
    return metrics.histogram(
        'skytpu_jobs_recovery_seconds',
        'Managed-job recovery duration (detection to relaunched)',
        buckets=LONG_WAIT_BUCKETS)


# Checkpoint saves run seconds-to-minutes (bucket write + retries), far
# below the provisioning waits but above serving latencies.
CHECKPOINT_SAVE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                           10.0, 30.0, 60.0, 120.0)


def checkpoint_save_hist() -> metrics.Histogram:
    return metrics.histogram(
        'skytpu_checkpoint_save_seconds',
        'Checkpoint save wall time (write + retries; off the step '
        'critical path for async saves)',
        buckets=CHECKPOINT_SAVE_BUCKETS)


def checkpoint_blocked_counter() -> metrics.Counter:
    return metrics.counter(
        'skytpu_checkpoint_blocked_seconds_total',
        'Seconds train steps spent blocked waiting on the bounded '
        'in-flight checkpoint save slot (nonzero means saves are '
        'slower than the save interval)')


def gang_resizes() -> metrics.Counter:
    return metrics.counter(
        'skytpu_gang_resizes_total',
        'Elastic gang resizes (shrink on partial preemption, expand '
        'when capacity returns)', labelnames=('direction',))
