"""Distributed trace assembly: one request's life across the fleet.

A disaggregated request touches several processes — LB queue → route →
KV handoff export (prefill replica) → import (decode replica) →
prefill/decode ticks → completion — and each process records only its
own leg (a `RequestSpan` in the engine, a `SegmentStore` entry on the
LB and the handoff endpoints).  This module stitches them:

- every process exports its segments over HTTP (`GET /spans` on the
  replica fronts, `GET /lb/spans` on the LB control plane), each
  tagged with `process` / `replica_id` / `role` / `attempt`;
- :func:`collect` fans those endpoints in for one request id;
- :func:`assemble` orders the segments causally (by wall start, LB
  attempts before the replica spans they produced);
- :func:`format_waterfall` renders the classic text waterfall
  (`sky serve trace <request-id>`);
- :func:`to_chrome_trace` / :func:`export_chrome_trace` emit the same
  segments as a Chrome trace (one pid per process, one tid per
  attempt) through utils/timeline.write_trace.

Clock caveat: segments carry *wall-clock* starts from different
machines; ordering is as honest as NTP.  Within one process the
ordering is exact.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)


def fetch_segments(url: str, path: str = http_protocol.SPANS,
                   request_id: Optional[str] = None,
                   since: Optional[float] = None,
                   timeout: float = 5.0) -> List[Dict[str, Any]]:
    """One process's exported segments; [] on any failure (assembly is
    best-effort — a dead replica must not kill the whole trace)."""
    params: Dict[str, Any] = {}
    if request_id is not None:
        params['request_id'] = request_id
    if since is not None:
        params['since'] = since
    try:
        resp = requests.get(url.rstrip('/') + path, params=params,
                            timeout=timeout)
        if resp.status_code != 200:
            return []
        return (resp.json() or {}).get('segments') or []
    except (requests.RequestException, ValueError) as e:
        logger.debug(f'span fetch failed for {url}: {e}')
        return []


def collect(request_id: str, replica_targets: List[Dict[str, Any]],
            lb_url: Optional[str] = None,
            timeout: float = 5.0) -> List[Dict[str, Any]]:
    """Fan in the fleet's segments for one request id.

    `replica_targets`: dicts with `url` (and optionally `replica_id`,
    `role` — used to tag segments from older replicas that predate
    identity tagging).  `lb_url`: the LB base url, queried on its
    `/lb/spans` control path."""
    segments: List[Dict[str, Any]] = []
    if lb_url:
        for seg in fetch_segments(lb_url, http_protocol.LB_SPANS,
                                  request_id=request_id,
                                  timeout=timeout):
            seg.setdefault('process', 'lb')
            segments.append(seg)
    for target in replica_targets:
        for seg in fetch_segments(target['url'], http_protocol.SPANS,
                                  request_id=request_id,
                                  timeout=timeout):
            seg.setdefault('process', 'replica')
            seg.setdefault('replica_id', target.get('replica_id'))
            seg.setdefault('role', target.get('role'))
            segments.append(seg)
    return assemble(segments)


def assemble(segments: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Causal order: wall start first; ties break LB-before-replica
    (the LB necessarily dispatched before the replica worked), then by
    attempt so a failed attempt renders before its retry."""
    def key(seg: Dict[str, Any]):
        return (float(seg.get('start') or 0.0),
                0 if seg.get('process') == 'lb' else 1,
                int(seg.get('attempt') or 0))

    return sorted((dict(s) for s in segments), key=key)


def _who(seg: Dict[str, Any]) -> str:
    proc = seg.get('process')
    if proc == 'lb':
        return 'lb'
    rid = seg.get('replica_id')
    role = seg.get('role')
    who = (f'replica {rid}' if rid is not None
           else str(proc or 'replica'))
    return f'{who} ({role})' if role else who


def format_waterfall(segments: List[Dict[str, Any]],
                     width: int = 40) -> List[str]:
    """Text waterfall, one line per segment plus indented phase lines:

        +0.000ms  lb                 route            ▕████▍      ▏
        +1.2ms    replica 1 (prefill) prefill_export  ▕  ██▊      ▏
    """
    if not segments:
        return ['(no segments)']
    t0 = min(float(s.get('start') or 0.0) for s in segments)
    t_end = max(float(s.get('start') or 0.0) +
                (float(s.get('duration_ms') or 0.0)) / 1e3
                for s in segments)
    total = max(t_end - t0, 1e-6)

    def bar(start: float, duration_ms: float) -> str:
        lo = int((start - t0) / total * width)
        hi = int((start - t0 + duration_ms / 1e3) / total * width)
        hi = max(hi, lo + 1)
        return ('.' * lo + '#' * (hi - lo) +
                '.' * max(0, width - hi))[:width]

    rows: List[List[str]] = []
    for seg in segments:
        start = float(seg.get('start') or 0.0)
        dur = float(seg.get('duration_ms') or 0.0)
        name = str(seg.get('name') or 'span')
        attempt = int(seg.get('attempt') or 0)
        label = name if attempt == 0 else f'{name}#{attempt}'
        status = seg.get('status')
        rows.append([f'+{(start - t0) * 1e3:.1f}ms', _who(seg), label,
                     f'{dur:.1f}ms',
                     str(status) if status is not None else '',
                     f'|{bar(start, dur)}|'])
        for phase in seg.get('phases') or []:
            p_start = float(phase.get('start') or start)
            p_dur = float(phase.get('duration_ms') or 0.0)
            detail = phase.get('target') or phase.get('status') or ''
            rows.append([f'+{(p_start - t0) * 1e3:.1f}ms', '',
                         f'  {phase.get("name", "?")}',
                         f'{p_dur:.1f}ms', str(detail),
                         f'|{bar(p_start, p_dur)}|'])
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    return ['  '.join(cell.ljust(w)
                      for cell, w in zip(row[:5], widths)).rstrip() +
            '  ' + row[5] for row in rows]


def fetch_log_records(url: str, path: str = http_protocol.LOGS,
                      timeout: float = 5.0,
                      **query: Any) -> List[Dict[str, Any]]:
    """One process's structured log records (`GET /logs` family); []
    on any failure — like spans, the fan-in is best-effort."""
    params = {k: v for k, v in query.items() if v is not None}
    try:
        resp = requests.get(url.rstrip('/') + path, params=params,
                            timeout=timeout)
        if resp.status_code != 200:
            return []
        return (resp.json() or {}).get('records') or []
    except (requests.RequestException, ValueError) as e:
        logger.debug(f'log fetch failed for {url}: {e}')
        return []


def interleave_logs(segments: List[Dict[str, Any]],
                    records: List[Dict[str, Any]],
                    width: int = 40) -> List[str]:
    """The waterfall with the request's log lines slotted in by wall
    time (`sky serve trace <rid>`): each record renders after the last
    segment/phase row that started at or before it, so a warning
    emitted mid-prefill reads under the prefill bar."""
    records = sorted(records, key=lambda r: float(r.get('ts') or 0.0))
    if not segments:
        if not records:
            return ['(no segments)']
        t0 = float(records[0].get('ts') or 0.0)
        return [_log_line(r, t0) for r in records]
    lines = format_waterfall(segments, width)
    # Row anchors mirror format_waterfall's emission order exactly:
    # one per segment, then one per phase of that segment.
    anchors: List[float] = []
    for seg in segments:
        start = float(seg.get('start') or 0.0)
        anchors.append(start)
        for phase in seg.get('phases') or []:
            anchors.append(float(phase.get('start') or start))
    t0 = min(float(s.get('start') or 0.0) for s in segments)
    out: List[str] = []
    ri = 0
    for line, anchor in zip(lines, anchors):
        while (ri < len(records) and
               float(records[ri].get('ts') or 0.0) < anchor):
            out.append(_log_line(records[ri], t0))
            ri += 1
        out.append(line)
    out.extend(_log_line(r, t0) for r in records[ri:])
    return out


def _log_line(record: Dict[str, Any], t0: float) -> str:
    ts = float(record.get('ts') or 0.0)
    level = str(record.get('level') or '?')
    msg = str(record.get('msg') or '')
    return (f'+{(ts - t0) * 1e3:.1f}ms  [{_who(record)}] '
            f'{level[:1]} {record.get("logger", "?")}: {msg}')


def to_chrome_trace(segments: List[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Segments -> Chrome trace events: one pid per process (named via
    'M' metadata events), one tid per attempt, segments and their
    phases as 'X' complete events."""
    pids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for seg in assemble(segments):
        who = _who(seg)
        pid = pids.get(who)
        if pid is None:
            pid = len(pids)
            pids[who] = pid
            events.append({'ph': 'M', 'name': 'process_name',
                           'pid': pid, 'tid': 0,
                           'args': {'name': who}})
        tid = int(seg.get('attempt') or 0)
        start = float(seg.get('start') or 0.0)
        dur = float(seg.get('duration_ms') or 0.0)
        args = {k: v for k, v in seg.items()
                if k not in ('phases',) and
                isinstance(v, (str, int, float, bool))}
        events.append({'ph': 'X',
                       'name': str(seg.get('name') or 'span'),
                       'cat': 'trace', 'pid': pid, 'tid': tid,
                       'ts': int(start * 1e6),
                       'dur': max(0, int(dur * 1e3)), 'args': args})
        for phase in seg.get('phases') or []:
            p_start = float(phase.get('start') or start)
            p_dur = float(phase.get('duration_ms') or 0.0)
            events.append({
                'ph': 'X', 'name': str(phase.get('name') or 'phase'),
                'cat': 'trace', 'pid': pid, 'tid': tid,
                'ts': int(p_start * 1e6),
                'dur': max(0, int(p_dur * 1e3)),
                'args': {k: v for k, v in phase.items()
                         if isinstance(v, (str, int, float, bool))}})
    return events


def export_chrome_trace(segments: List[Dict[str, Any]],
                        path: str) -> None:
    """Write the stitched trace as a standalone Chrome trace file
    (reuses timeline.write_trace — same format `status --events
    --export-trace` emits)."""
    timeline.write_trace(path, to_chrome_trace(segments))
