"""SLO tracking: objectives from the service spec, evaluated
multi-window / multi-burn-rate against the fleet telemetry store.

The service spec's optional ``slos:`` block names the objectives:

    service:
      slos:
        ttft_p99_ms: 500      # 99% of requests see first token <= 500ms
        itl_p99_ms: 100       # 99% of inter-token gaps <= 100ms
        error_rate: 0.01      # <= 1% of LB requests fail upstream
        availability: 0.999   # <= 0.1% of LB requests see no replica

Each objective defines an *error budget* (1% of requests may exceed
the TTFT threshold, etc.).  The tracker computes the **burn rate** —
the fraction of budget being consumed per unit time, i.e.
``bad_fraction / budget`` — over a FAST and a SLOW trailing window
(Google SRE multi-window multi-burn-rate alerting: the fast window
catches a fresh regression quickly, the slow window keeps one noisy
scrape from paging).  A breach requires the burn rate above threshold
in BOTH windows; recovery requires the fast window back under it.

Breach transitions are journaled (``slo_burn_start`` /
``slo_burn_end`` in ``events/serve.jsonl`` — the same flight recorder
the drain lifecycle uses) and exported as gauges
(``skytpu_slo_burn_rate{slo,window}``, ``skytpu_slo_breached{slo}``),
and `sky serve top` renders the live status.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import aggregator as aggregator_lib
from skypilot_tpu.observability import metrics as metrics_lib

logger = sky_logging.init_logger(__name__)

_M_BURN = metrics_lib.gauge(
    'skytpu_slo_burn_rate',
    'Error-budget burn rate per SLO and evaluation window (1.0 = '
    'consuming budget exactly as fast as the objective allows).',
    ('service', 'slo', 'window'))
_M_BREACHED = metrics_lib.gauge(
    'skytpu_slo_breached',
    'Whether the SLO is currently breaching (burn rate above '
    'threshold in both windows).', ('service', 'slo'))

# The slos: block vocabulary (service_spec validates against this).
SLO_KEYS = ('ttft_p99_ms', 'itl_p99_ms', 'error_rate', 'availability')


def fast_window_s() -> float:
    return float(os.environ.get('SKYTPU_SLO_FAST_WINDOW_S', '60'))


def slow_window_s() -> float:
    return float(os.environ.get('SKYTPU_SLO_SLOW_WINDOW_S', '300'))


def burn_threshold() -> float:
    return float(os.environ.get('SKYTPU_SLO_BURN_THRESHOLD', '1.0'))


@dataclasses.dataclass
class SLO:
    """One objective: how to measure its bad fraction + the budget."""
    name: str                  # the slos: key, e.g. 'ttft_p99_ms'
    kind: str                  # 'latency' | 'error_rate' | 'availability'
    budget: float              # allowed bad fraction (e.g. 0.01)
    threshold_s: float = 0.0   # latency SLOs: the bound in seconds
    series: str = ''           # latency SLOs: the histogram base name
    target: float = 0.0        # the raw spec value (for display)


def parse_slos(slos: Optional[Dict[str, Any]]) -> List[SLO]:
    """The spec's slos: block -> SLO objects (service_spec already
    validated keys and ranges)."""
    out: List[SLO] = []
    if not slos:
        return out
    if 'ttft_p99_ms' in slos:
        out.append(SLO('ttft_p99_ms', 'latency', budget=0.01,
                       threshold_s=float(slos['ttft_p99_ms']) / 1e3,
                       series='skytpu_engine_ttft_seconds',
                       target=float(slos['ttft_p99_ms'])))
    if 'itl_p99_ms' in slos:
        out.append(SLO('itl_p99_ms', 'latency', budget=0.01,
                       threshold_s=float(slos['itl_p99_ms']) / 1e3,
                       series='skytpu_engine_itl_seconds',
                       target=float(slos['itl_p99_ms'])))
    if 'error_rate' in slos:
        rate = float(slos['error_rate'])
        out.append(SLO('error_rate', 'error_rate', budget=rate,
                       target=rate))
    if 'availability' in slos:
        avail = float(slos['availability'])
        out.append(SLO('availability', 'availability',
                       budget=1.0 - avail, target=avail))
    return out


def _bad_fraction(slo: SLO, store: 'aggregator_lib.TimeSeriesStore',
                  window_s: float, now: float) -> Optional[float]:
    """Fraction of the window's events that violate the objective;
    None when the window holds no traffic (no traffic = no burn)."""
    if slo.kind == 'latency':
        deltas = store.bucket_deltas(slo.series, window_s, now)
        if not deltas:
            return None
        total = max(deltas.values())  # cumulative: +Inf (or top) bucket
        if total <= 0:
            return None
        # Good = observations at or under the threshold: the tightest
        # bucket bound >= threshold (conservative when the threshold
        # falls between bounds).
        good_bounds = [b for b in deltas if b >= slo.threshold_s]
        good = deltas[min(good_bounds)] if good_bounds else 0.0
        return max(0.0, 1.0 - good / total)
    requests = store.counter_rate('skytpu_lb_requests_total',
                                  window_s, now)
    if not requests:
        return None
    if slo.kind == 'error_rate':
        bad = (store.counter_rate('skytpu_lb_upstream_errors_total',
                                  window_s, now) or 0.0)
    else:  # availability
        bad = (store.counter_rate('skytpu_lb_no_replica_total',
                                  window_s, now) or 0.0)
    return min(1.0, bad / requests)


class SLOTracker:
    """Evaluate the objectives each reconcile pass; journal breaches."""

    def __init__(self, service_name: str, slos: List[SLO],
                 journal: Optional[Any] = None) -> None:
        self.service_name = service_name
        self.slos = slos
        self._journal = journal
        # slo name -> breach start ts while breaching.
        self._breaching: Dict[str, float] = {}
        self._last: List[Dict[str, Any]] = []

    def _get_journal(self):
        if self._journal is not None:
            return self._journal
        from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
        return events_lib.get_journal(
            os.path.join(events_lib.journal_root(), 'serve.jsonl'))

    def _journal_event(self, event: str, **fields: Any) -> None:
        try:
            self._get_journal().append(event, service=self.service_name,
                                       **fields)
        except Exception:  # pylint: disable=broad-except
            pass  # recording must never break the control plane

    def evaluate(self, store: 'aggregator_lib.TimeSeriesStore',
                 now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass; returns (and caches) per-SLO status
        dicts for `/controller/telemetry`."""
        now = time.time() if now is None else now
        fast_w, slow_w = fast_window_s(), slow_window_s()
        threshold = burn_threshold()
        out: List[Dict[str, Any]] = []
        for slo in self.slos:
            burns = {}
            for window_name, window in (('fast', fast_w),
                                        ('slow', slow_w)):
                bad = _bad_fraction(slo, store, window, now)
                burn = (bad / slo.budget) if (
                    bad is not None and slo.budget > 0) else 0.0
                burns[window_name] = burn
                _M_BURN.labels(service=self.service_name, slo=slo.name,
                               window=window_name).set(round(burn, 6))
            was_breaching = slo.name in self._breaching
            if not was_breaching:
                breaching = (burns['fast'] > threshold and
                             burns['slow'] > threshold)
            else:
                # Recovery needs only the fast window back under the
                # threshold: the slow window keeps the breach's history
                # long after the regression is fixed.
                breaching = burns['fast'] > threshold
            if breaching and not was_breaching:
                self._breaching[slo.name] = now
                self._journal_event(
                    'slo_burn_start', slo=slo.name, target=slo.target,
                    burn_fast=round(burns['fast'], 4),
                    burn_slow=round(burns['slow'], 4),
                    window_fast_s=fast_w, window_slow_s=slow_w)
                logger.warning(
                    f'SLO {slo.name} breaching for '
                    f'{self.service_name}: burn fast='
                    f'{burns["fast"]:.2f} slow={burns["slow"]:.2f} '
                    f'(threshold {threshold})')
            elif not breaching and was_breaching:
                started = self._breaching.pop(slo.name)
                self._journal_event(
                    'slo_burn_end', slo=slo.name,
                    duration_s=round(now - started, 3),
                    burn_fast=round(burns['fast'], 4))
                logger.info(f'SLO {slo.name} recovered for '
                            f'{self.service_name} after '
                            f'{now - started:.0f}s')
            _M_BREACHED.labels(service=self.service_name,
                               slo=slo.name).set(1.0 if breaching
                                                 else 0.0)
            out.append({
                'slo': slo.name, 'kind': slo.kind,
                'target': slo.target, 'budget': slo.budget,
                'burn_fast': round(burns['fast'], 4),
                'burn_slow': round(burns['slow'], 4),
                'breaching': breaching,
                'since': self._breaching.get(slo.name),
            })
        self._last = out
        return out

    def status(self) -> List[Dict[str, Any]]:
        """The most recent evaluation (for the telemetry endpoint)."""
        return list(self._last)
