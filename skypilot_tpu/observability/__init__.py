"""Unified observability layer: metrics + request tracing.

One dependency-free substrate every layer reports into (SURVEY.md
north star: a production service is only as debuggable as its
telemetry):

- `metrics`: Counter/Gauge/Histogram instruments with label support, a
  process-global registry, and Prometheus text-format exposition — the
  serving fronts answer `GET /metrics` from it, the training callback
  feeds step telemetry into it.
- `tracing`: request-id generation + per-request span records (queue
  wait, prefill, TTFT, ITL, total decode) propagated load_balancer →
  server → batching-engine slot via the `X-SkyTPU-Request-Id` header,
  and emitted into the Chrome-trace timeline (utils/timeline.py).
- `events`: the control-plane flight recorder — per-cluster / per-job
  JSONL event journals, `ControlSpan` phase spans over the launch and
  recovery paths, and the `skytpu_provision_* / skytpu_gang_* /
  skytpu_skylet_* / skytpu_jobs_*` fleet-health series.
- `aggregator`: the controller-side fleet telemetry plane — a bounded
  ring-buffer time-series store scraped from every replica + the LB,
  with windowed rates/quantiles, smoothed autoscaler signals, and
  per-replica MFU gauges.
- `slo`: service-level objectives from the spec's `slos:` block,
  evaluated multi-window / multi-burn-rate against the aggregator
  store, with breaches journaled as `slo_burn_start/_end`.
- `traces`: cross-process trace assembly — every process exports its
  span segments (`GET /spans`, `GET /lb/spans`) and `sky serve trace`
  stitches them into one waterfall / Chrome trace.

See docs/observability.md for the metrics catalog, the request-id
propagation diagram, and the control-plane event schema.
"""
from skypilot_tpu.observability import events
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing

__all__ = ['events', 'metrics', 'tracing']
