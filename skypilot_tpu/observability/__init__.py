"""Unified observability layer: metrics + request tracing.

One dependency-free substrate every layer reports into (SURVEY.md
north star: a production service is only as debuggable as its
telemetry):

- `metrics`: Counter/Gauge/Histogram instruments with label support, a
  process-global registry, and Prometheus text-format exposition — the
  serving fronts answer `GET /metrics` from it, the training callback
  feeds step telemetry into it.
- `tracing`: request-id generation + per-request span records (queue
  wait, prefill, TTFT, ITL, total decode) propagated load_balancer →
  server → batching-engine slot via the `X-SkyTPU-Request-Id` header,
  and emitted into the Chrome-trace timeline (utils/timeline.py).

See docs/observability.md for the metrics catalog and the request-id
propagation diagram.
"""
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import tracing

__all__ = ['metrics', 'tracing']
