"""Perf-regression observatory: an append-only history of bench runs
and noise-aware run-over-run diffing.

`bench.py` and `bench_serve.py` append one JSON line per run — config,
git rev, throughput, latency quantiles, MFU estimate, and the
profiler's tick-phase breakdown — to a committed `BENCH_history.jsonl`
at the repo root (`SKYTPU_BENCH_HISTORY_PATH` overrides; the pinned
smoke runs write to a throwaway path so CI never churns the committed
file).  `sky bench diff` compares the newest run of each
(metric, config) group against its predecessors and exits non-zero on
regression.

The threshold is noise-aware: a key regresses when its relative change
in the bad direction exceeds ``max(min_rel, noise_k x cv)`` where
``cv`` is the coefficient of variation (stdev/mean) of the baseline
runs — a naturally jittery series needs a bigger move to count than a
dead-flat one.
"""
from __future__ import annotations

import json
import os
import statistics
import subprocess
import time
from typing import Any, Dict, List, Optional

HISTORY_BASENAME = 'BENCH_history.jsonl'

# Direction per comparable key: True = larger is better.
HIGHER_IS_BETTER = {
    'value': True,
    'tokens_per_s': True,
    'mfu_estimate': True,
    'ttft_p50_ms': False,
    'ttft_p99_ms': False,
    'itl_p50_ms': False,
    'itl_p99_ms': False,
}

DEFAULT_MIN_REL = 0.10   # ignore moves under 10% regardless of noise
DEFAULT_NOISE_K = 3.0    # 3-sigma-of-relative-noise gate


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def history_path(path: Optional[str] = None) -> str:
    if path:
        return path
    env = os.environ.get('SKYTPU_BENCH_HISTORY_PATH')
    if env:
        return env
    return os.path.join(repo_root(), HISTORY_BASENAME)


def git_rev() -> Optional[str]:
    """Short git rev of the working tree (None outside a checkout —
    history must append fine from an exported tarball)."""
    try:
        out = subprocess.run(
            ['git', 'rev-parse', '--short', 'HEAD'],
            cwd=repo_root(), capture_output=True, text=True,
            timeout=10, check=False)
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev or None


def append_record(record: Dict[str, Any],
                  path: Optional[str] = None) -> str:
    """Append one run record (stamping ts/git_rev when absent);
    returns the path written."""
    record = dict(record)
    record.setdefault('ts', time.time())
    if 'git_rev' not in record:
        record['git_rev'] = git_rev()
    target = history_path(path)
    parent = os.path.dirname(target)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(target, 'a', encoding='utf-8') as f:
        f.write(json.dumps(record, sort_keys=True) + '\n')
    return target


def load_records(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every parseable record, file order (malformed lines skipped —
    a truncated append must not brick the observatory)."""
    target = history_path(path)
    if not os.path.exists(target):
        return []
    records: List[Dict[str, Any]] = []
    with open(target, 'r', encoding='utf-8') as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def group_key(record: Dict[str, Any]) -> str:
    """Runs are comparable when metric AND config match — a slots=8
    run never baselines a slots=64 one."""
    return json.dumps({'metric': record.get('metric'),
                       'config': record.get('config')}, sort_keys=True)


def diff_records(records: List[Dict[str, Any]],
                 last: Optional[int] = None,
                 min_rel: float = DEFAULT_MIN_REL,
                 noise_k: float = DEFAULT_NOISE_K
                 ) -> List[Dict[str, Any]]:
    """Compare each group's newest run against its baseline (the
    `last` preceding runs; default: all of them).

    Returns one finding per comparable key of each group with >= 2
    runs: baseline mean, latest value, relative change, the noise-aware
    threshold, and whether the move is a regression (bad direction,
    over threshold).  Improvements and in-noise moves carry
    ``regression: False`` so callers can render the whole picture."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        groups.setdefault(group_key(rec), []).append(rec)
    findings: List[Dict[str, Any]] = []
    for key, runs in groups.items():
        runs = sorted(runs, key=lambda r: r.get('ts') or 0.0)
        if len(runs) < 2:
            continue
        latest = runs[-1]
        baseline_runs = runs[:-1]
        if last is not None and last > 0:
            baseline_runs = baseline_runs[-last:]
        meta = json.loads(key)
        for field, higher_better in HIGHER_IS_BETTER.items():
            cur = latest.get(field)
            prior = [r[field] for r in baseline_runs
                     if isinstance(r.get(field), (int, float))]
            if not isinstance(cur, (int, float)) or not prior:
                continue
            base = statistics.fmean(prior)
            if base == 0:
                continue
            cv = (statistics.pstdev(prior) / abs(base)
                  if len(prior) > 1 else 0.0)
            threshold = max(min_rel, noise_k * cv)
            change = (cur - base) / abs(base)
            worse = (change < 0) if higher_better else (change > 0)
            findings.append({
                'metric': meta['metric'],
                'config': meta['config'],
                'field': field,
                'baseline': base,
                'baseline_runs': len(prior),
                'latest': cur,
                'latest_rev': latest.get('git_rev'),
                'change': change,
                'threshold': threshold,
                'regression': bool(worse and abs(change) > threshold),
            })
    return findings


def format_findings(findings: List[Dict[str, Any]]) -> List[str]:
    """Human lines, regressions first."""
    lines: List[str] = []
    ordered = sorted(findings,
                     key=lambda f: (not f['regression'],
                                    str(f['metric']), f['field']))
    for f in ordered:
        flag = 'REGRESSION' if f['regression'] else 'ok'
        lines.append(
            f"[{flag}] {f['metric']} {f['field']}: "
            f"{f['baseline']:.4g} -> {f['latest']:.4g} "
            f"({f['change']:+.1%}, threshold ±{f['threshold']:.0%}, "
            f"baseline n={f['baseline_runs']}"
            + (f", rev {f['latest_rev']}" if f.get('latest_rev')
               else '') + ')')
    return lines
