"""Request tracing: ids, per-request spans, and timeline emission.

Answers "why was THIS request slow": every request carries an id (the
`X-SkyTPU-Request-Id` header, generated at the outermost layer that
sees the request — load balancer, else server front, else engine) and
the batching engine records a `RequestSpan` per request with the
phase breakdown a serving SLO decomposes into:

    queue_wait  — submit() until the engine pops the request
    prefill     — chunked prompt prefill (count + total seconds)
    ttft        — submit() until the first generated token
    itl         — inter-token gaps during decode (count/mean/max)
    total       — submit() until the request finished

Finished spans land in a bounded `SpanStore` (newest-first, surfaced
through `engine.stats()['recent_spans']` → `/health`) and are emitted
into the Chrome-trace timeline (utils/timeline.py) as `X` complete
events, so `SKYTPU_TIMELINE_FILE=trace.json` shows per-request
queue/prefill/decode bars next to the control-plane spans.

Span bookkeeping is mutation-from-one-thread (the engine worker) plus
read-from-any (stats()); the store's lock covers the handoff.

Fleet telemetry (PR 11) turns these per-process spans into *trace
segments*: every process exports its spans through `GET /spans` (the
replica fronts) / `GET /lb/spans` (the load balancer), each segment
tagged with process identity (`process`, `replica_id`, `role`) and the
LB `attempt` number, so `sky serve trace <request-id>` can stitch one
request's life across the disaggregated fleet
(observability/traces.py does the assembly).
"""
from __future__ import annotations

import collections
import threading
import time
import uuid
from typing import Any, Deque, Dict, List, Optional

from skypilot_tpu.serve import http_protocol
from skypilot_tpu.utils import timeline

# Propagated load_balancer -> model_server/async_server -> engine slot;
# servers echo it on the response so clients can correlate.
# (Re-exported from the canonical serve/http_protocol.py module.)
REQUEST_ID_HEADER = http_protocol.REQUEST_ID_HEADER

# Spans kept per store; old spans fall off (a replica serving millions
# of requests must not grow without bound).
DEFAULT_STORE_SIZE = 256
# Spans inlined into stats() -> /health (the store keeps more).
STATS_SPAN_LIMIT = 8


def new_request_id() -> str:
    """16 hex chars: unique enough per fleet, short enough for logs."""
    return uuid.uuid4().hex[:16]


def parse_span_query(query: str) -> Dict[str, Any]:
    """`GET /spans` / `GET /lb/spans` query args -> export kwargs
    (`since`, `request_id`, `limit`); malformed values are ignored,
    not 400s — the trace CLI must degrade, never fail, on version
    skew."""
    from urllib.parse import parse_qs  # pylint: disable=import-outside-toplevel
    parsed = parse_qs(query or '')
    out: Dict[str, Any] = {}
    if parsed.get('request_id'):
        out['request_id'] = parsed['request_id'][0]
    for key in ('since', 'limit'):
        if parsed.get(key):
            try:
                value = float(parsed[key][0])
                out[key] = int(value) if key == 'limit' else value
            except ValueError:
                pass
    return out


class RequestSpan:
    """Phase timings of one serving request (times are monotonic
    internally; wall-clock start is kept for the timeline)."""

    def __init__(self, request_id: Optional[str] = None) -> None:
        self.request_id = request_id or new_request_id()
        self.submit_wall = time.time()
        self._submit = time.monotonic()
        self.queue_wait_s: Optional[float] = None
        self.prefill_chunks = 0
        self.prefill_s = 0.0
        # Prompt pages adopted from the engine's prefix cache instead
        # of prefilled (paged-KV engines; 0 = cold / dense engine).
        self.prefix_hit_pages = 0
        # Router facts (disaggregated serving): which role pool the LB
        # picked, whether prefix affinity hit, and how long the KV
        # page handoff took.  None when the request bypassed the LB.
        self.routed_role: Optional[str] = None
        self.affinity_hit: Optional[bool] = None
        self.handoff_ms: Optional[float] = None
        # LB retry attempt that produced this span (X-SkyTPU-Attempt).
        # The router's one-shot same-role retry reuses the request id
        # on a SECOND replica; without the attempt tag the two
        # processes' spans conflate on assembly.  None = not LB-routed
        # (reads as attempt 0).
        self.attempt: Optional[int] = None
        # Multi-host slice replicas: mean coordinated-tick sync
        # overhead (rank-0 broadcast until every rank acked) while this
        # request was in flight.  None on single-host replicas.
        self.slice_sync_ms: Optional[float] = None
        # Self-speculative decoding (engines with --spec-tokens > 0):
        # verify ticks this request rode, draft tokens proposed for it,
        # and drafts accepted — the per-request acceptance story behind
        # the engine-level skytpu_engine_spec_* counters.  All stay 0
        # (and the dict fields absent) when spec decoding is off.
        self.spec_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        # Which weight epoch served this request (live weight swap:
        # POST /weights_swap bumps the engine's epoch; every span
        # records the epoch in force at submit so batch output rows
        # can attribute each generation to a checkpoint).  None on
        # engines predating the swap path.
        self.weight_epoch: Optional[int] = None
        self.ttft_s: Optional[float] = None
        self._last_token: Optional[float] = None
        self.itl_count = 0
        self.itl_sum_s = 0.0
        self.itl_max_s = 0.0
        self.tokens = 0
        self.total_s: Optional[float] = None
        self.status: Optional[str] = None

    # ----------------------------------------------- recording (engine)

    def mark_admitted(self) -> None:
        if self.queue_wait_s is None:
            self.queue_wait_s = time.monotonic() - self._submit

    def mark_prefill_chunk(self, duration_s: float) -> None:
        self.prefill_chunks += 1
        self.prefill_s += duration_s

    def mark_token(self) -> Optional[float]:
        """Record one generated token; returns the inter-token gap in
        seconds (None for the first token — that one sets TTFT)."""
        now = time.monotonic()
        self.tokens += 1
        gap: Optional[float] = None
        if self.ttft_s is None:
            self.ttft_s = now - self._submit
        elif self._last_token is not None:
            gap = now - self._last_token
            self.itl_count += 1
            self.itl_sum_s += gap
            self.itl_max_s = max(self.itl_max_s, gap)
        self._last_token = now
        return gap

    def finish(self, status: str = 'ok') -> None:
        if self.total_s is not None:
            return  # idempotent like _Request._finish
        self.total_s = time.monotonic() - self._submit
        self.status = status
        self._emit_timeline()

    # ------------------------------------------------------------ output

    def to_dict(self) -> Dict[str, Any]:
        def ms(v: Optional[float]) -> Optional[float]:
            return None if v is None else round(v * 1e3, 3)

        itl_mean = (self.itl_sum_s / self.itl_count
                    if self.itl_count else None)
        out = {
            'request_id': self.request_id,
            'submit_time': self.submit_wall,
            'status': self.status,
            'queue_wait_ms': ms(self.queue_wait_s),
            'prefill_chunks': self.prefill_chunks,
            'prefill_ms': ms(self.prefill_s),
            'prefix_hit_pages': self.prefix_hit_pages,
            'ttft_ms': ms(self.ttft_s),
            'itl_mean_ms': ms(itl_mean),
            'itl_max_ms': ms(self.itl_max_s if self.itl_count else None),
            'tokens': self.tokens,
            'total_ms': ms(self.total_s),
        }
        # Router fields appear only for LB-routed requests: span dicts
        # predating disaggregation keep their exact shape.
        if self.routed_role is not None:
            out['routed_role'] = self.routed_role
        if self.affinity_hit is not None:
            out['affinity_hit'] = self.affinity_hit
        if self.handoff_ms is not None:
            out['handoff_ms'] = round(self.handoff_ms, 3)
        if self.slice_sync_ms is not None:
            out['slice_sync_ms'] = round(self.slice_sync_ms, 3)
        if self.attempt is not None:
            out['attempt'] = self.attempt
        if self.weight_epoch is not None:
            out['weight_epoch'] = self.weight_epoch
        if self.spec_steps:
            out['spec_steps'] = self.spec_steps
            out['spec_proposed'] = self.spec_proposed
            out['spec_accepted'] = self.spec_accepted
            # Mean tokens emitted per verify tick (>= 1.0; the verified
            # base token always emits, accepted drafts ride on top).
            out['spec_accept_mean'] = round(
                (self.spec_accepted + self.spec_steps) /
                self.spec_steps, 3)
        return out

    def segment(self, identity: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
        """This span as a trace segment: the cross-process exchange
        format of `GET /spans` (see observability/traces.py).  The
        phase sub-spans mirror `_emit_timeline`'s bars so the stitched
        waterfall and the live timeline agree."""
        seg: Dict[str, Any] = dict(identity or {})
        seg.setdefault('process', 'replica')
        seg.setdefault('name', 'engine')
        seg.update(self.to_dict())
        seg['attempt'] = self.attempt or 0
        seg['start'] = self.submit_wall
        seg['duration_ms'] = seg.pop('total_ms', None)
        phases: List[Dict[str, Any]] = []
        wall0 = self.submit_wall
        if self.queue_wait_s:
            phases.append({'name': 'queue', 'start': wall0,
                           'duration_ms': round(
                               self.queue_wait_s * 1e3, 3)})
        if self.prefill_s:
            phases.append({'name': 'prefill',
                           'start': wall0 + (self.queue_wait_s or 0.0),
                           'duration_ms': round(self.prefill_s * 1e3,
                                                3)})
        if self.ttft_s is not None and self.total_s is not None:
            phases.append({'name': 'decode',
                           'start': wall0 + self.ttft_s,
                           'duration_ms': round(
                               (self.total_s - self.ttft_s) * 1e3, 3)})
        seg['phases'] = phases
        return seg

    def _emit_timeline(self) -> None:
        if not timeline.enabled():
            return
        base = f'request:{self.request_id}'
        wall0 = self.submit_wall
        timeline.add_complete_event(
            base, wall0, self.total_s or 0.0,
            args={k: v for k, v in self.to_dict().items()
                  if v is not None})
        if self.queue_wait_s:
            timeline.add_complete_event(f'{base}/queue', wall0,
                                        self.queue_wait_s)
        if self.ttft_s is not None:
            # Prefill runs between admission and first token; the span
            # bar shows its aggregate (chunks interleave with ticks, so
            # a contiguous bar is an approximation labeled as such).
            if self.prefill_s:
                timeline.add_complete_event(
                    f'{base}/prefill',
                    wall0 + (self.queue_wait_s or 0.0), self.prefill_s,
                    args={'chunks': self.prefill_chunks})
            decode_s = (self.total_s or self.ttft_s) - self.ttft_s
            timeline.add_complete_event(
                f'{base}/decode', wall0 + self.ttft_s, decode_s,
                args={'tokens': self.tokens})


class SpanStore:
    """Bounded newest-first store of finished spans."""

    def __init__(self, maxlen: int = DEFAULT_STORE_SIZE) -> None:
        self._spans: Deque[RequestSpan] = collections.deque(
            maxlen=maxlen)
        self._lock = threading.Lock()

    def add(self, span: RequestSpan) -> None:
        with self._lock:
            self._spans.append(span)

    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for span in reversed(self._spans):
                if span.request_id == request_id:
                    return span.to_dict()
        return None

    def recent(self, n: int = STATS_SPAN_LIMIT) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._spans)[-n:]
        return [s.to_dict() for s in reversed(spans)]

    def export(self, identity: Optional[Dict[str, Any]] = None,
               since: Optional[float] = None,
               request_id: Optional[str] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Finished spans as identity-tagged trace segments (the
        `GET /spans?since=&request_id=` payload), oldest first."""
        with self._lock:
            spans = list(self._spans)
        out = []
        for span in spans:
            if since is not None and span.submit_wall < since:
                continue
            if request_id is not None and \
                    span.request_id != request_id:
                continue
            out.append(span.segment(identity))
        if limit is not None:
            out = out[-int(limit):]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


class SegmentStore:
    """Bounded store of already-built trace segments (plain dicts).

    The LB and the handoff endpoints record here: their work is not an
    engine request (no RequestSpan exists), but it IS a leg of some
    request's life — `/prefill_export` on the prefill replica, the
    route/handoff/attempt phases on the LB.  Same export contract as
    SpanStore so `sky serve trace` stitches both."""

    def __init__(self, maxlen: int = DEFAULT_STORE_SIZE) -> None:
        self._segments: Deque[Dict[str, Any]] = collections.deque(
            maxlen=maxlen)
        self._lock = threading.Lock()

    def add(self, segment: Dict[str, Any]) -> None:
        with self._lock:
            self._segments.append(segment)

    def export(self, since: Optional[float] = None,
               request_id: Optional[str] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            segments = list(self._segments)
        out = []
        for seg in segments:
            if since is not None and seg.get('start', 0.0) < since:
                continue
            if request_id is not None and \
                    seg.get('request_id') != request_id:
                continue
            out.append(dict(seg))
        if limit is not None:
            out = out[-int(limit):]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._segments)
