"""Fleet log plane: structured request-scoped logs + error-spike alerts.

The fourth observability pillar (after metrics, traces/SLOs, and
profiling): every log record the framework emits is captured — in
addition to stderr — into a bounded in-process ring of structured
entries:

    {seq, ts, level, logger, msg,
     process, replica_id, role,      # who said it
     request_id, attempt}            # on whose behalf

The identity fields come from a **contextvar** that each serving layer
binds around the request it is handling (the HTTP fronts, the LB
routed path, the engine worker admission, the coordinator's follower
executor), reusing the `X-SkyTPU-Request-Id` / `X-SkyTPU-Attempt`
propagation the tracing plane already ships — so a log line emitted
three processes away from the client still knows which request it
belongs to.  contextvars survive `await` boundaries natively; thread
handoffs (`run_in_executor`, the engine worker) re-bind explicitly.

The ring is exported over `GET /logs?since=&level=&request_id=&grep=
&limit=` on the replica fronts (`/lb/logs`, `/controller/logs` for the
other processes); `since=` is an exact **seq cursor** (records with
`seq > since`), so paginating exporters never see a record twice and
never miss one that survived the ring bound (same contract the span
stores pin in test_span_store_concurrency.py).

`skytpu_log_records_total{level}` counts captured records; the fleet
aggregator scrapes it per replica and `LogSpikeTracker` turns the
WARN+ERROR rate into `log_error_spike_start/_end` journal alerts with
the same fast/slow-window shape as SLO burn (a spike needs the rate
over threshold in BOTH windows; recovery needs the fast window back
under).  `sky serve top` renders the rate as the ERR/s column.
"""
from __future__ import annotations

import collections
import contextlib
import contextvars
import logging
import os
import re
import threading
import time
from typing import Any, Deque, Dict, Iterator, List, Optional

from skypilot_tpu.serve import http_protocol

# Default bound on the in-process record ring.  ~2k records of ~200
# bytes keeps the whole plane under a megabyte per process.
DEFAULT_RING_RECORDS = 2048

# Levels that count toward the error-spike rate.
_BAD_LEVELS = ('WARNING', 'ERROR', 'CRITICAL')

# Series name shared by the handler counter and the spike tracker.
LOG_RECORDS_SERIES = 'skytpu_log_records_total'


def ring_records() -> int:
    try:
        return int(os.environ.get('SKYTPU_LOG_RING_RECORDS',
                                  str(DEFAULT_RING_RECORDS)))
    except ValueError:
        return DEFAULT_RING_RECORDS


def spike_fast_window_s() -> float:
    return float(os.environ.get('SKYTPU_LOG_ERROR_SPIKE_FAST_WINDOW_S',
                                '60'))


def spike_slow_window_s() -> float:
    return float(os.environ.get('SKYTPU_LOG_ERROR_SPIKE_SLOW_WINDOW_S',
                                '300'))


def spike_threshold() -> float:
    """WARN+ERROR records per second above which a replica spikes."""
    return float(os.environ.get('SKYTPU_LOG_ERROR_SPIKE_THRESHOLD',
                                '1.0'))


# --------------------------------------------------------------- context

# One merged dict of bound fields (request_id/attempt/process/
# replica_id/role).  asyncio tasks inherit it at creation; executor
# threads need contextvars.copy_context().run (see wrap_context).
_CTX: 'contextvars.ContextVar[Optional[Dict[str, Any]]]' = \
    contextvars.ContextVar('skytpu_log_ctx', default=None)

# Process-level fallback identity: the normal one-server-per-process
# deployment sets it once at startup; tests hosting several "processes"
# in one interpreter rely on the contextvar binding instead.
_process_identity: Dict[str, Any] = {}


def set_process_identity(process: str,
                         replica_id: Optional[Any] = None,
                         role: Optional[str] = None) -> None:
    """Default identity stamped on records with no bound context."""
    _process_identity.clear()
    _process_identity['process'] = process
    if replica_id is not None:
        _process_identity['replica_id'] = replica_id
    if role is not None:
        _process_identity['role'] = role


@contextlib.contextmanager
def bind(request_id: Optional[str] = None,
         attempt: Optional[int] = None,
         process: Optional[str] = None,
         replica_id: Optional[Any] = None,
         role: Optional[str] = None) -> Iterator[None]:
    """Bind request/identity fields for log records emitted inside the
    context (merging over any outer binding; None fields inherit)."""
    merged = dict(_CTX.get() or {})
    for key, value in (('request_id', request_id), ('attempt', attempt),
                       ('process', process), ('replica_id', replica_id),
                       ('role', role)):
        if value is not None:
            merged[key] = value
    token = _CTX.set(merged)
    try:
        yield
    finally:
        _CTX.reset(token)


def current_context() -> Dict[str, Any]:
    """The fields a record emitted right now would carry (bound
    context over the process fallback)."""
    out = dict(_process_identity)
    out.update(_CTX.get() or {})
    return out


def wrap_context(fn):
    """Carry the CURRENT context into a thread-pool callable: asyncio's
    `run_in_executor` runs the function in a bare worker thread where
    contextvars reset to defaults — the classic request-id-loss bug."""
    ctx = contextvars.copy_context()
    return lambda *args, **kwargs: ctx.run(fn, *args, **kwargs)


# ------------------------------------------------------------------ ring

def parse_log_query(query: str) -> Dict[str, Any]:
    """`GET /logs` query args -> export kwargs; malformed values are
    ignored, not 400s (same degradation contract as
    tracing.parse_span_query — the CLI must survive version skew)."""
    from urllib.parse import parse_qs  # pylint: disable=import-outside-toplevel
    parsed = parse_qs(query or '')
    out: Dict[str, Any] = {}
    for key in ('request_id', 'level', 'grep'):
        if parsed.get(key):
            out[key] = parsed[key][0]
    for key in ('since', 'limit'):
        if parsed.get(key):
            try:
                value = float(parsed[key][0])
                out[key] = int(value) if key == 'limit' else value
            except ValueError:
                pass
    return out


def _level_no(level: Any) -> Optional[int]:
    """'warning' / 'WARNING' / '30' -> 30; unknown names -> None
    (filter ignored rather than rejected)."""
    if level is None:
        return None
    text = str(level).strip()
    if not text:
        return None
    try:
        return int(float(text))
    except ValueError:
        pass
    resolved = logging.getLevelName(text.upper())
    return resolved if isinstance(resolved, int) else None


class LogRecordRing:
    """Bounded ring of structured log records with exact `since=` seq
    pagination (strictly-after cursor; seq is unique + monotonic)."""

    def __init__(self, maxlen: Optional[int] = None) -> None:
        self._records: Deque[Dict[str, Any]] = collections.deque(
            maxlen=maxlen if maxlen is not None else ring_records())
        self._lock = threading.Lock()
        self._seq = 0

    def add(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            record['seq'] = self._seq
            self._records.append(record)

    def export(self, since: Optional[float] = None,
               level: Any = None,
               request_id: Optional[str] = None,
               grep: Optional[str] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Matching records oldest-first; `since` is a seq cursor
        (records with seq > since), `level` a minimum severity,
        `grep` a regex (substring fallback on a bad pattern),
        `limit` keeps the newest n."""
        with self._lock:
            records = list(self._records)
        min_no = _level_no(level)
        pattern = None
        if grep:
            try:
                pattern = re.compile(grep)
            except re.error:
                pattern = None
        out = []
        for rec in records:
            if since is not None and rec['seq'] <= since:
                continue
            if min_no is not None and rec.get('levelno', 0) < min_no:
                continue
            if request_id is not None and \
                    rec.get('request_id') != request_id:
                continue
            if grep:
                msg = str(rec.get('msg', ''))
                if pattern is not None:
                    if not pattern.search(msg):
                        continue
                elif grep not in msg:
                    continue
            out.append(dict(rec))
        if limit is not None:
            out = out[-int(limit):]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


_global_ring: Optional[LogRecordRing] = None
_ring_lock = threading.Lock()


def get_ring() -> LogRecordRing:
    """The process-wide ring the installed handler writes to."""
    global _global_ring
    with _ring_lock:
        if _global_ring is None:
            _global_ring = LogRecordRing()
        return _global_ring


def reset_ring() -> LogRecordRing:
    """Swap in a fresh ring (tests; re-reads the env cap).  Handlers
    constructed without an explicit ring resolve through get_ring()
    on every emit, so they follow the swap."""
    global _global_ring
    with _ring_lock:
        _global_ring = LogRecordRing()
        return _global_ring


# --------------------------------------------------------------- metrics

def _records_counter():
    """Lazy: sky_logging._setup installs the handler during the FIRST
    init_logger call, which can happen while metrics.py itself is
    still importing — instruments must not be created at import."""
    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    # Literal name (= LOG_RECORDS_SERIES): the metrics-catalog lint
    # ties doc rows to statically visible registrations.
    return metrics_lib.counter(
        'skytpu_log_records_total',
        'Log records captured by the structured handler, per level.',
        ('level',))


def _http_counter():
    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    return metrics_lib.counter(
        'skytpu_http_requests_total',
        'HTTP requests served by the serving fronts, per route and '
        'status code.', ('route', 'code'))


def _spike_gauges():
    from skypilot_tpu.observability import metrics as metrics_lib  # pylint: disable=import-outside-toplevel
    rate = metrics_lib.gauge(
        'skytpu_log_error_rate',
        'Windowed WARN+ERROR log records per second, per replica and '
        'evaluation window.', ('service', 'replica_id', 'window'))
    spiking = metrics_lib.gauge(
        'skytpu_log_error_spiking',
        'Whether the replica is inside a log error spike (rate above '
        'threshold in both windows).', ('service', 'replica_id'))
    return rate, spiking


# -------------------------------------------------------------- handler

class StructuredLogHandler(logging.Handler):
    """Capture every framework record into the ring + level counter.

    emit() is on the path of every log call the process makes, so it
    does the minimum: getMessage, one dict, one deque append, one
    counter bump — and never raises (a broken observability plane must
    not take the serving plane with it)."""

    def __init__(self, ring: Optional[LogRecordRing] = None) -> None:
        super().__init__(level=logging.DEBUG)
        self._ring = ring

    def emit(self, record: logging.LogRecord) -> None:
        try:
            entry: Dict[str, Any] = {
                'ts': record.created,
                'level': record.levelname,
                'levelno': record.levelno,
                'logger': record.name,
                'msg': record.getMessage(),
            }
            entry.update(_process_identity)
            bound = _CTX.get()
            if bound:
                entry.update(bound)
            (self._ring or get_ring()).add(entry)
            _records_counter().labels(level=record.levelname).inc()
        except Exception:  # pylint: disable=broad-except
            pass


# ----------------------------------------------------------- access logs

# Scrape/probe hot paths whose per-request access lines log at DEBUG:
# the controller polls them every few seconds and the ring must not be
# wall-to-wall scrape noise.  Generation routes stay at INFO.
# ('/health' is the replica fronts' catch-all GET, not a canonical
# protocol path — every other entry comes from http_protocol.)
HEALTH_ROUTE = '/health'
PROBE_ROUTES = (HEALTH_ROUTE, http_protocol.METRICS,
                http_protocol.SPANS, http_protocol.PROFILE,
                http_protocol.LOGS, http_protocol.LB_METRICS,
                http_protocol.LB_SPANS, http_protocol.LB_STATE,
                http_protocol.LB_LOGS)


def access_log(logger: logging.Logger, method: str, route: str,
               code: int) -> None:
    """Count + log one served HTTP request.  `route` must be the
    matched route constant, never the raw path (label cardinality)."""
    try:
        _http_counter().labels(route=route, code=str(code)).inc()
    except Exception:  # pylint: disable=broad-except
        pass
    level = logging.DEBUG if route in PROBE_ROUTES else logging.INFO
    logger.log(level, f'{method} {route} -> {code}')


# ---------------------------------------------------------- spike alerts

def error_rates(store: Any, window_s: float, now: float
                ) -> Dict[str, float]:
    """Per-replica WARN+ERROR records/s from the scraped fleet store:
    {replica_id: rate} over every replica whose log counter the
    aggregator has seen (the scraper stamps replica_id/role labels on
    every ingested series)."""
    rates: Dict[str, float] = {}
    rids = {labels.get('replica_id')
            for labels, _ in store.series(LOG_RECORDS_SERIES)
            if labels.get('replica_id') not in (None, '')}
    for rid in sorted(rids):
        total = None
        for level in _BAD_LEVELS:
            rate = store.counter_rate(LOG_RECORDS_SERIES, window_s,
                                      now, replica_id=rid, level=level)
            if rate is not None:
                total = (total or 0.0) + rate
        if total is not None:
            rates[str(rid)] = total
    return rates


class LogSpikeTracker:
    """Journal `log_error_spike_start/_end` per replica — the same
    multi-window shape as SLO burn: a spike needs the WARN+ERROR rate
    above threshold in BOTH the fast and slow windows; recovery needs
    the fast window back under it."""

    def __init__(self, service_name: str,
                 journal: Optional[Any] = None) -> None:
        self.service_name = service_name
        self._journal = journal
        # replica_id -> spike start ts while spiking.
        self._spiking: Dict[str, float] = {}
        self._last: List[Dict[str, Any]] = []

    def _get_journal(self):
        if self._journal is not None:
            return self._journal
        from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
        return events_lib.get_journal(
            os.path.join(events_lib.journal_root(), 'serve.jsonl'))

    def _journal_event(self, event: str, **fields: Any) -> None:
        try:
            self._get_journal().append(event,
                                       service=self.service_name,
                                       **fields)
        except Exception:  # pylint: disable=broad-except
            pass  # recording must never break the control plane

    def evaluate(self, store: Any, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One pass over the fleet store; returns (and caches)
        per-replica status dicts for `/controller/telemetry`."""
        now = time.time() if now is None else now
        fast_w, slow_w = spike_fast_window_s(), spike_slow_window_s()
        threshold = spike_threshold()
        fast = error_rates(store, fast_w, now)
        slow = error_rates(store, slow_w, now)
        gauge_rate, gauge_spiking = _spike_gauges()
        logger = logging.getLogger(
            'skypilot_tpu.observability.logs')
        out: List[Dict[str, Any]] = []
        for rid in sorted(set(fast) | set(slow) | set(self._spiking)):
            rate_fast = fast.get(rid, 0.0)
            rate_slow = slow.get(rid, 0.0)
            for window, rate in (('fast', rate_fast),
                                 ('slow', rate_slow)):
                gauge_rate.labels(service=self.service_name,
                                  replica_id=rid,
                                  window=window).set(round(rate, 6))
            was_spiking = rid in self._spiking
            if not was_spiking:
                spiking = (rate_fast > threshold and
                           rate_slow > threshold)
            else:
                # Recovery needs only the fast window back under: the
                # slow window remembers the spike long after the
                # replica quiets down.
                spiking = rate_fast > threshold
            if spiking and not was_spiking:
                self._spiking[rid] = now
                self._journal_event(
                    'log_error_spike_start', replica_id=rid,
                    rate_fast=round(rate_fast, 4),
                    rate_slow=round(rate_slow, 4),
                    threshold=threshold,
                    window_fast_s=fast_w, window_slow_s=slow_w)
                logger.warning(
                    f'log error spike on replica {rid} of '
                    f'{self.service_name}: {rate_fast:.2f} err/s fast '
                    f'/ {rate_slow:.2f} slow (threshold {threshold})')
            elif not spiking and was_spiking:
                started = self._spiking.pop(rid)
                self._journal_event(
                    'log_error_spike_end', replica_id=rid,
                    duration_s=round(now - started, 3),
                    rate_fast=round(rate_fast, 4))
                logger.info(
                    f'log error spike on replica {rid} of '
                    f'{self.service_name} ended after '
                    f'{now - started:.0f}s')
            gauge_spiking.labels(service=self.service_name,
                                 replica_id=rid).set(
                                     1.0 if spiking else 0.0)
            out.append({
                'replica_id': rid,
                'rate_fast': round(rate_fast, 4),
                'rate_slow': round(rate_slow, 4),
                'threshold': threshold,
                'spiking': spiking,
                'since': self._spiking.get(rid),
            })
        self._last = out
        return out

    def status(self) -> List[Dict[str, Any]]:
        """The most recent evaluation (for the telemetry endpoint)."""
        return list(self._last)
